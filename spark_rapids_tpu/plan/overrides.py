"""TpuOverrides — the planner/override engine (GpuOverrides analog).

Reference behavior being reproduced (`GpuOverrides.scala:4619-4775`,
`RapidsMeta.scala`, `GpuTransitionOverrides.scala`):
- wrap every logical node in a meta, tag device support with reasons
  (per-operator granularity; one unsupported expression sends just that
  operator to CPU),
- convert the plan to physical operators (Tpu* or Cpu* fallback),
- insert the physical necessities: partial/final aggregation around
  exchanges, co-partitioning exchanges for joins, single-partition
  exchange for global sort/limit, and host<->device transitions at every
  backend boundary (GpuRowToColumnarExec/GpuColumnarToRowExec roles),
- explain-only mode: report the would-be placement without executing
  (`spark.rapids.sql.mode=explainOnly`, `explainPotentialGpuPlan`
  GpuOverrides.scala:4500).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from spark_rapids_tpu.config import rapids_conf as rc
from spark_rapids_tpu.exec import operators as ops
from spark_rapids_tpu.exec.base import PhysicalPlan
from spark_rapids_tpu.expr import Alias, BoundReference
from spark_rapids_tpu.plan import logical as L
from spark_rapids_tpu.plan.typesig import (
    expr_unsupported_reasons,
    key_type_supported,
)


class PlanMeta:
    """Tagging record for one logical node (RapidsMeta analog)."""

    def __init__(self, node: L.LogicalPlan):
        self.node = node
        self.reasons: List[str] = []
        self.children: List[PlanMeta] = []

    @property
    def can_run_on_device(self) -> bool:
        return not self.reasons

    def cannot_run(self, reason: str):
        self.reasons.append(reason)

    def explain(self, indent: int = 0, only_not_on_device=True) -> str:
        tag = ("*" if self.can_run_on_device else
               "!NOT_ON_TPU " + "; ".join(self.reasons))
        lines = []
        if not only_not_on_device or not self.can_run_on_device:
            lines.append("  " * indent +
                         f"{type(self.node).__name__} {tag}")
        for c in self.children:
            sub = c.explain(indent + 1, only_not_on_device)
            if sub:
                lines.append(sub)
        return "\n".join([ln for ln in lines if ln])


class TpuOverrides:
    def __init__(self, conf: rc.RapidsConf):
        self.conf = conf
        self.metas: List[PlanMeta] = []

    # ----- tagging -----

    def tag(self, node: L.LogicalPlan) -> PlanMeta:
        meta = PlanMeta(node)
        if not self.conf.get(rc.SQL_ENABLED):
            meta.cannot_run("spark.rapids.sql.enabled is false")
        op_name = type(node).__name__
        if not self.conf.exec_enabled(op_name):
            # per-exec switch (spark.rapids.sql.exec.<Name>=false —
            # the GpuOverrides exec-registry disable surface)
            meta.cannot_run(
                f"{op_name} disabled via spark.rapids.sql.exec."
                f"{op_name}=false")
        if self.conf.get(rc.CPU_ORACLE_ENABLED):
            meta.cannot_run("cpu-oracle session")
        elif isinstance(node, L.Project):
            for e in node.exprs:
                for r in expr_unsupported_reasons(e, self.conf):
                    meta.cannot_run(r)
        elif isinstance(node, L.Filter):
            for r in expr_unsupported_reasons(node.condition, self.conf):
                meta.cannot_run(r)
        elif isinstance(node, L.Aggregate):
            from spark_rapids_tpu.expr.aggregates import Max, Min
            from spark_rapids_tpu.sqltypes import StringType

            for e in node.grouping + node.aggregates:
                for r in expr_unsupported_reasons(e, self.conf):
                    meta.cannot_run(r)
            for g in node.grouping:
                r = key_type_supported(g.dtype)
                if r:
                    meta.cannot_run(r)
            from spark_rapids_tpu.expr.aggregates import (
                CollectList, CountDistinct, Percentile, _Bivariate,
                _Moments,
            )
            from spark_rapids_tpu.sqltypes import (
                ArrayType as _AT,
                NumericType as _NT,
            )

            for a in node.aggregates:
                fn = a.children[0]
                if (isinstance(fn, (Min, Max)) and fn.input is not None
                        and isinstance(fn.input.dtype, StringType)):
                    meta.cannot_run(
                        "string min/max aggregation runs on CPU in v1")
                from spark_rapids_tpu.plan.typesig import _wide_dec

                if (isinstance(fn, (CollectList, CountDistinct))
                        and fn.input is not None
                        and (isinstance(fn.input.dtype, (StringType, _AT))
                             or _wide_dec(fn.input.dtype))):
                    meta.cannot_run(
                        "collect/distinct over string/array/decimal128 "
                        "input runs on CPU in v1")
                if isinstance(fn, (_Moments, _Bivariate, Percentile)):
                    for e in fn.children:
                        if not isinstance(e.dtype, _NT):
                            meta.cannot_run(
                                f"{fn.name} requires numeric input")
        elif isinstance(node, L.Join):
            for e in node.left_keys + node.right_keys:
                for r in expr_unsupported_reasons(e, self.conf):
                    meta.cannot_run(r)
                r = key_type_supported(e.dtype)
                if r:
                    meta.cannot_run(r)
            if node.condition is not None:
                for r in expr_unsupported_reasons(node.condition, self.conf):
                    meta.cannot_run(r)
        elif isinstance(node, L.Sort):
            for o in node.orders:
                for r in expr_unsupported_reasons(o.expr, self.conf):
                    meta.cannot_run(r)
                r = key_type_supported(o.expr.dtype)
                if r:
                    meta.cannot_run(r)
        elif isinstance(node, L.Generate):
            for e in node.pass_through:
                for r in expr_unsupported_reasons(e, self.conf):
                    meta.cannot_run(r)
            gen_input = node.gen_alias.children[0].children[0]
            for r in expr_unsupported_reasons(gen_input, self.conf):
                meta.cannot_run(r)
        elif isinstance(node, L.Expand):
            for p in node.projections:
                for e in p:
                    for r in expr_unsupported_reasons(e, self.conf):
                        meta.cannot_run(r)
        elif isinstance(node, L.Sample):
            if node.with_replacement:
                meta.cannot_run("with-replacement sampling has no "
                                "fixed-shape device lowering (CPU)")
        elif isinstance(node, (L.MapInPandas, L.GroupedMapInPandas,
                               L.CoGroupedMapInPandas)):
            meta.cannot_run(
                "pandas exchange runs via the Arrow worker pool "
                "(GpuArrowEvalPythonExec family is host-side in the "
                "reference too)")
        elif isinstance(node, L.Window):
            self._tag_window(node, meta)
        elif isinstance(node, L.FileScan):
            from spark_rapids_tpu.plan.typesig import type_supported

            fmt_entry = rc._FMT_READ_ENTRIES.get(node.fmt)
            if fmt_entry is not None and not self.conf.get(fmt_entry):
                meta.cannot_run(
                    f"{node.fmt} reads disabled via {fmt_entry.key}")
            for f in node.schema.fields:
                r = type_supported(f.dataType)
                if r:
                    meta.cannot_run(f"column {f.name!r}: {r}")
        elif isinstance(node, L.LocalRelation):
            meta.cannot_run("in-memory relation stays host-side until "
                            "first device operator")
        # CachedRelation: always device-capable (the entry IS device
        # batches), no tagging required
        meta.children = [self.tag(c) for c in node.children]
        self.metas.append(meta)
        return meta

    def _tag_window(self, node: "L.Window", meta: PlanMeta):
        from spark_rapids_tpu.expr import windows as we
        from spark_rapids_tpu.expr.aggregates import (
            Average, CollectList, Count, First, Last, Max, Min,
            StddevPop, StddevSamp, Sum, VariancePop, VarianceSamp,
        )
        from spark_rapids_tpu.sqltypes import (
            ArrayType,
            MapType,
            NumericType,
            StringType,
        )

        supported_aggs = (Sum, Count, Min, Max, Average, First, Last,
                          VariancePop, VarianceSamp, StddevPop,
                          StddevSamp, CollectList)
        from spark_rapids_tpu.sqltypes import StructType as _St

        for f in node.children[0].schema.fields:
            if isinstance(f.dataType, _St):
                # the window exec rebuilds pass-through columns
                # leaf-wise via the sort permutation scatter; no
                # children-aware path yet
                meta.cannot_run(
                    f"struct payload column {f.name!r}: device window "
                    "has no struct lowering")
        for a in node.window_exprs:
            wexpr = a.children[0]
            for e in wexpr.spec.partitions:
                for r in expr_unsupported_reasons(e, self.conf):
                    meta.cannot_run(r)
            for o in wexpr.spec.orders:
                for r in expr_unsupported_reasons(o.expr, self.conf):
                    meta.cannot_run(r)
            fn = wexpr.function
            if isinstance(fn, we.WindowFunction):
                if fn.needs_order and not wexpr.spec.orders:
                    meta.cannot_run(
                        f"{type(fn).__name__} requires ORDER BY")
                if isinstance(fn, we.Lead):
                    for r in expr_unsupported_reasons(fn.input, self.conf):
                        meta.cannot_run(r)
                    if fn.default is not None:
                        for r in expr_unsupported_reasons(fn.default, self.conf):
                            meta.cannot_run(r)
            elif isinstance(fn, supported_aggs):
                from spark_rapids_tpu.plan.typesig import _wide_dec as _wd

                if fn.input is not None and _wd(fn.input.dtype):
                    meta.cannot_run(
                        "decimal(>18) window aggregation runs on CPU "
                        "in v1")
                if fn.input is not None:
                    for r in expr_unsupported_reasons(fn.input, self.conf):
                        meta.cannot_run(r)
                    if (isinstance(fn.input.dtype,
                                   (ArrayType, MapType))
                            and not isinstance(fn, CollectList)):
                        # frame kernels take flat/2-D inputs; array
                        # payloads (incl. the array<string> cube) have
                        # no first/last/min-max frame lowering
                        meta.cannot_run(
                            f"window {type(fn).__name__} over "
                            f"{fn.input.dtype.simpleString} runs on CPU")
                if (isinstance(fn, (Min, Max)) and
                        isinstance(fn.input.dtype, StringType)):
                    meta.cannot_run(
                        "string min/max over window frames runs on CPU")
                if isinstance(fn, CollectList):  # CollectSet subclasses
                    frame = wexpr.spec.frame
                    bounded = (frame is not None
                               and frame.frame_type == "rows"
                               and frame.lower is not None
                               and frame.upper is not None)
                    if not bounded:
                        meta.cannot_run(
                            "window collect over unbounded frames runs "
                            "on CPU (device output width is the static "
                            "frame span)")
                    elif int(frame.upper) - int(frame.lower) + 1 > 1024:
                        # the device kernel materializes a [rows, span]
                        # element matrix — wide frames belong on CPU
                        meta.cannot_run(
                            "window collect frame span > 1024 runs on "
                            "CPU")
                    elif isinstance(fn.input.dtype,
                                    (StringType, ArrayType, MapType)):
                        # frame_collect gathers a [cap, W] element
                        # matrix — only flat scalar elements fit
                        meta.cannot_run(
                            "window collect of string/array/map "
                            "elements runs on CPU")
            else:
                meta.cannot_run(f"window function {type(fn).__name__} "
                                "has no device implementation")
            frame = wexpr.spec.frame
            if (frame is not None and frame.frame_type == "range" and
                    (frame.lower not in (None, 0) or
                     frame.upper not in (None, 0))):
                orders = wexpr.spec.orders
                if (len(orders) != 1 or not orders[0].ascending or
                        not isinstance(orders[0].expr.dtype, NumericType)):
                    meta.cannot_run(
                        "RANGE frame offsets need one ascending numeric "
                        "ORDER BY key on device")

    # ----- conversion -----

    def apply(self, plan: L.LogicalPlan) -> Tuple[PhysicalPlan, PlanMeta]:
        meta = self.tag(plan)
        from spark_rapids_tpu.plan import cbo

        if self.conf.get(cbo.OPTIMIZER_ENABLED):
            cbo.apply_cbo(meta, self.conf)
        phys = self._convert(meta)
        explain_mode = self.conf.get(rc.EXPLAIN)
        if explain_mode != "NONE":
            txt = meta.explain(only_not_on_device=explain_mode ==
                               "NOT_ON_GPU")
            if txt:
                print(txt)
        return phys, meta

    def _to_device(self, child: PhysicalPlan) -> PhysicalPlan:
        if child.is_tpu:
            return child
        return ops.ArrowToDeviceExec(child, self.conf)

    def _gather_host(self, child: PhysicalPlan) -> PhysicalPlan:
        """Host child funneled to ONE partition (global grouping)."""
        host = self._to_host(child)
        if host.num_partitions > 1:
            return ops.CpuShuffleExchangeExec(host, None, 1, self.conf)
        return host

    def _to_host(self, child: PhysicalPlan) -> PhysicalPlan:
        if not child.is_tpu:
            return child
        return ops.DeviceToArrowExec(child, self.conf)

    def _convert(self, meta: PlanMeta) -> PhysicalPlan:
        node = meta.node
        conf = self.conf
        on_device = meta.can_run_on_device

        if isinstance(node, L.LocalRelation):
            return ops.LocalRelationExec(node.table, node.schema, conf)
        if isinstance(node, L.CachedRelation):
            return ops.TpuCachedRelationExec(node.entry, node.schema,
                                             conf)
        if isinstance(node, L.Range):
            return ops.RangeExec(node.start, node.end, node.step,
                                 node.num_partitions, node.schema, conf)
        if isinstance(node, L.FileScan):
            cols = node.schema.names
            filters = getattr(node, "pushed_filters", None)
            if on_device:
                scan = ops.TpuFileScanExec(node.fmt, node.paths,
                                           node.schema, conf,
                                           pushed_columns=cols,
                                           pushed_filters=filters,
                                           options=node.options)
                if conf.get(rc.COALESCE_AFTER_SCAN):
                    # chunked scans feed many small batches; coalesce
                    # toward batchSizeRows before per-batch consumers
                    # (GpuCoalesceBatches after-scan insertion)
                    return ops.TpuCoalesceBatchesExec(scan, conf)
                return scan
            return ops.CpuFileScanExec(node.fmt, node.paths, node.schema,
                                       conf, pushed_columns=cols,
                                       pushed_filters=filters,
                                       options=node.options)

        if isinstance(node, L.Limit):
            smeta = meta.children[0]
            if (isinstance(smeta.node, L.Sort) and smeta.node.global_sort
                    and on_device and smeta.can_run_on_device):
                # TakeOrderedAndProject fusion (GpuOverrides.scala:4084):
                # per-partition sort+limit, gather, final sort+limit —
                # never materializes more than n rows per partition
                inner = self._to_device(self._convert(smeta.children[0]))
                return self._take_ordered(node.n, smeta.node.orders,
                                          inner)

        children = [self._convert(c) for c in meta.children]

        if isinstance(node, L.Project):
            if on_device:
                return ops.TpuProjectExec(node.exprs,
                                          self._to_device(children[0]),
                                          node.schema, conf)
            return ops.CpuProjectExec(node.exprs, self._to_host(children[0]),
                                      node.schema, conf)
        if isinstance(node, L.Filter):
            if on_device:
                return ops.TpuFilterExec(node.condition,
                                         self._to_device(children[0]), conf)
            return ops.CpuFilterExec(node.condition,
                                     self._to_host(children[0]), conf)
        if isinstance(node, L.Expand):
            if on_device:
                return ops.TpuExpandExec(node.projections,
                                         self._to_device(children[0]),
                                         node.schema, conf)
            return ops.CpuExpandExec(node.projections,
                                     self._to_host(children[0]),
                                     node.schema, conf)
        if isinstance(node, L.Sample):
            if on_device:
                return ops.TpuSampleExec(node.fraction, node.seed,
                                         self._to_device(children[0]), conf)
            return ops.CpuSampleExec(node.fraction, node.seed,
                                     node.with_replacement,
                                     self._to_host(children[0]), conf)
        if isinstance(node, L.MapInPandas):
            # map is per-row: partition layout is irrelevant
            return ops.CpuMapInPandasExec(
                node.fn, node.schema, self._to_host(children[0]), conf)
        if isinstance(node, L.GroupedMapInPandas):
            # grouping must be GLOBAL: gather multi-partition children
            # (the aggregate path inserts the same exchange)
            return ops.CpuGroupedMapInPandasExec(
                node.key_names, node.fn, node.schema,
                self._gather_host(children[0]), conf)
        if isinstance(node, L.CoGroupedMapInPandas):
            return ops.CpuCoGroupedMapInPandasExec(
                node.key_names, node.fn, node.schema,
                self._gather_host(children[0]),
                self._gather_host(children[1]), conf)
        if isinstance(node, L.Aggregate):
            return self._convert_aggregate(node, children[0], on_device)
        if isinstance(node, L.Join):
            return self._convert_join(node, children, on_device)
        if isinstance(node, L.Sort):
            return self._convert_sort(node, children[0], on_device)
        if isinstance(node, L.Generate):
            if on_device:
                return ops.TpuGenerateExec(
                    node.pass_through, node.gen_alias, node.position,
                    self._to_device(children[0]), conf)
            return ops.CpuGenerateExec(
                node.pass_through, node.gen_alias, node.position,
                self._to_host(children[0]), conf)
        if isinstance(node, L.Window):
            return self._convert_window(node, children[0], on_device)
        if isinstance(node, L.Limit):
            return self._convert_limit(node, children[0], on_device)
        if isinstance(node, L.Union):
            tpu = all(c.is_tpu for c in children)
            kids = ([self._to_device(c) for c in children] if tpu
                    else [self._to_host(c) for c in children])
            return ops.UnionExec(kids, node.schema, conf, tpu)
        if isinstance(node, L.Repartition):
            child = children[0]
            keys = node.keys
            if on_device and (child.is_tpu or keys is not None):
                # no coalesce wrap: the exchange's reduce side already
                # re-slices fetched blocks at batchSizeRows (the
                # GpuShuffleCoalesceExec discipline), and downstream
                # isinstance-based exchange bypasses must keep matching
                return ops.TpuShuffleExchangeExec(
                    self._to_device(child), keys, node.num_partitions,
                    conf)
            return ops.CpuShuffleExchangeExec(self._to_host(child), keys,
                                              node.num_partitions, conf)
        raise NotImplementedError(f"logical node {type(node).__name__}")

    def _convert_aggregate(self, node: L.Aggregate, child: PhysicalPlan,
                           on_device: bool) -> PhysicalPlan:
        conf = self.conf
        shuffle_parts = conf.get(rc.SHUFFLE_PARTITIONS)
        if not on_device:
            return ops.CpuHashAggregateExec(
                node.grouping, node.aggregates,
                ops.CpuShuffleExchangeExec(
                    self._to_host(child), None, 1, conf)
                if child.num_partitions > 1 else self._to_host(child),
                node.schema, conf)
        child = self._to_device(child)
        if child.num_partitions == 1:
            return ops.TpuHashAggregateExec(
                "complete", node.grouping, node.aggregates, child, conf)
        partial = ops.TpuHashAggregateExec(
            "partial", node.grouping, node.aggregates, child, conf)
        if node.grouping:
            key_refs = [BoundReference(i, g.dtype)
                        for i, g in enumerate(node.grouping)]
            exchange = ops.TpuShuffleExchangeExec(
                partial, key_refs, shuffle_parts, conf)
        else:
            exchange = ops.TpuShuffleExchangeExec(partial, None, 1, conf)
        return ops.TpuHashAggregateExec(
            "final", node.grouping, node.aggregates, exchange, conf)

    def _convert_join(self, node: L.Join, children: List[PhysicalPlan],
                      on_device: bool) -> PhysicalPlan:
        from spark_rapids_tpu.exec.joins import swap_condition

        conf = self.conf
        left, right = children
        if not on_device:
            return ops.CpuJoinExec(
                self._single(self._to_host(left)),
                self._single(self._to_host(right)),
                node.join_type, node.left_keys, node.right_keys,
                node.schema, conf, condition=node.condition)
        shuffle_parts = conf.get(rc.SHUFFLE_PARTITIONS)
        left = self._to_device(left)
        right = self._to_device(right)
        join_type = node.join_type
        left_keys, right_keys = node.left_keys, node.right_keys
        condition = node.condition
        n_l = len(node.children[0].schema.fields)
        n_r = len(node.children[1].schema.fields)
        build_logical = node.children[1]
        swapped = join_type == "right"
        if swapped:
            # right outer = swapped left outer + column reorder
            left, right = right, left
            left_keys, right_keys = right_keys, left_keys
            join_type = "left"
            build_logical = node.children[0]
            if condition is not None:
                condition = swap_condition(condition, n_l, n_r)
        exec_schema = (self._swapped_schema(left, right) if swapped
                       else node.schema)
        if not left_keys or join_type == "cross":
            joined = self._nested_loop_join(
                left, right, join_type, condition, exec_schema)
        else:
            joined = self._hash_join(
                left, right, join_type, left_keys, right_keys, condition,
                exec_schema, build_logical, shuffle_parts)
        if not swapped:
            return joined
        # swapped layout is [orig-right fields | orig-left fields];
        # reorder back to node.schema = [left | right]
        swapped_schema = joined.schema
        reorder = [Alias(BoundReference(n_r + i,
                                        swapped_schema.fields[n_r + i]
                                        .dataType, True),
                         swapped_schema.fields[n_r + i].name)
                   for i in range(n_l)]
        reorder += [Alias(BoundReference(i,
                                         swapped_schema.fields[i].dataType,
                                         True),
                          swapped_schema.fields[i].name)
                    for i in range(n_r)]
        return ops.TpuProjectExec(reorder, joined, node.schema, conf)

    def _swapped_schema(self, left, right):
        from spark_rapids_tpu.sqltypes import StructField, StructType

        return StructType(
            [StructField(f.name, f.dataType, True)
             for f in left.schema.fields] +
            [StructField(f.name, f.dataType, f.nullable)
             for f in right.schema.fields])

    def _hash_join(self, left, right, join_type, left_keys, right_keys,
                   condition, exec_schema, build_logical, shuffle_parts):
        conf = self.conf
        threshold = conf.get(rc.BROADCAST_THRESHOLD)
        est = L.estimate_size_bytes(build_logical)
        broadcastable = (threshold >= 0 and est is not None and
                         est <= threshold and
                         join_type in ("inner", "left", "left_semi",
                                       "left_anti", "existence"))
        if broadcastable:
            return ops.TpuBroadcastHashJoinExec(
                left, right, join_type, left_keys, right_keys,
                exec_schema, conf, condition=condition)
        both_single = (left.num_partitions == 1 and
                       right.num_partitions == 1)
        if not both_single:
            left = ops.TpuShuffleExchangeExec(
                left, left_keys, shuffle_parts, conf)
            right = ops.TpuShuffleExchangeExec(
                right, right_keys, shuffle_parts, conf)
        return ops.TpuShuffledHashJoinExec(
            left, right, join_type, left_keys, right_keys,
            exec_schema, conf, condition=condition)

    def _nested_loop_join(self, left, right, join_type, condition,
                          exec_schema):
        conf = self.conf
        if join_type == "full":
            # build-match tracking must be partition-local
            left = self._single(left)
        return ops.TpuBroadcastNestedLoopJoinExec(
            left, right, join_type, exec_schema, conf,
            condition=condition)

    def _single(self, plan: PhysicalPlan) -> PhysicalPlan:
        if plan.num_partitions == 1:
            return plan
        if plan.is_tpu:
            return ops.TpuShuffleExchangeExec(plan, None, 1, self.conf)
        return ops.CpuShuffleExchangeExec(plan, None, 1, self.conf)

    def _take_ordered(self, n: int, orders, child: PhysicalPlan
                      ) -> PhysicalPlan:
        conf = self.conf
        local = ops.TpuLocalLimitExec(
            n, ops.TpuSortExec(orders, child, conf), conf)
        if local.num_partitions > 1:
            local = ops.TpuLocalLimitExec(
                n, ops.TpuSortExec(
                    orders,
                    ops.TpuShuffleExchangeExec(local, None, 1, conf),
                    conf), conf)
        return local

    def _convert_sort(self, node: L.Sort, child: PhysicalPlan,
                      on_device: bool) -> PhysicalPlan:
        conf = self.conf
        if not on_device:
            return ops.CpuSortExec(node.orders,
                                   self._single(self._to_host(child)), conf)
        child = self._to_device(child)
        if node.global_sort and child.num_partitions > 1:
            # distributed global sort: sample-based range exchange, then
            # per-partition out-of-core sort; partition order == global
            # order (GpuRangePartitioner.scala + GpuSortExec.scala)
            child = ops.TpuRangeShuffleExchangeExec(
                child, node.orders, conf.get(rc.SHUFFLE_PARTITIONS), conf)
        return ops.TpuSortExec(node.orders, child, conf)

    def _convert_window(self, node: "L.Window", child: PhysicalPlan,
                        on_device: bool) -> PhysicalPlan:
        conf = self.conf
        if not on_device:
            return ops.CpuWindowExec(
                node.window_exprs, self._single(self._to_host(child)),
                node.schema, conf)
        child = self._to_device(child)
        spec = node.window_exprs[0].children[0].spec
        if child.num_partitions > 1:
            if spec.partitions:
                child = ops.TpuShuffleExchangeExec(
                    child, spec.partitions,
                    conf.get(rc.SHUFFLE_PARTITIONS), conf)
            else:
                child = ops.TpuShuffleExchangeExec(child, None, 1, conf)
        halo = ops.window_halo(node.window_exprs)
        chunk_rows = conf.get(rc.BATCH_SIZE_ROWS)
        if halo is not None and halo > chunk_rows // 2:
            # the batched path peeks at most one following chunk for the
            # suffix halo; frames wider than half a chunk must take the
            # whole-partition path for correctness
            halo = None
        from spark_rapids_tpu.plan.logical import SortOrder

        def chunked_sort_child():
            # out-of-core sort on the partition+order keys emitting
            # bounded chunks (shared by the halo and running paths)
            orders = ([SortOrder(p, True) for p in spec.partitions] +
                      list(spec.orders))
            return ops.TpuSortExec(orders, child, conf,
                                   chunk_rows=chunk_rows)

        if halo is not None and (spec.partitions or spec.orders):
            # bounded-frame batched window, evaluated with halo
            # context (GpuBatchedBoundedWindowExec role)
            return ops.TpuWindowExec(node.window_exprs,
                                     chunked_sort_child(), conf,
                                     presorted=True, halo=halo)
        mode = (ops.window_streaming_mode(node.window_exprs)
                if conf.get(rc.WINDOW_STREAMING) else None)
        if mode == "running" and spec.orders:
            # running frames / ranking: sorted chunks + carried scan
            # state (GpuRunningWindowExec role) — O(chunk) residency
            return ops.TpuWindowExec(node.window_exprs,
                                     chunked_sort_child(), conf,
                                     presorted=True, mode="running")
        if mode == "u2u":
            # whole-partition aggregates: two-pass partial+lookup
            # (GpuUnboundedToUnboundedAggWindowExec role), no sort
            return ops.TpuWindowExec(node.window_exprs, child, conf,
                                     mode="u2u")
        return ops.TpuWindowExec(node.window_exprs, child, conf)

    def _convert_limit(self, node: L.Limit, child: PhysicalPlan,
                       on_device: bool) -> PhysicalPlan:
        conf = self.conf
        if not on_device:
            local = ops.CpuLocalLimitExec(node.n, self._to_host(child), conf)
            if local.num_partitions > 1:
                local = ops.CpuLocalLimitExec(
                    node.n, ops.CpuShuffleExchangeExec(local, None, 1, conf),
                    conf)
            return local
        child = self._to_device(child)
        local = ops.TpuLocalLimitExec(node.n, child, conf)
        if local.num_partitions > 1:
            local = ops.TpuLocalLimitExec(
                node.n, ops.TpuShuffleExchangeExec(local, None, 1, conf),
                conf)
        return local


def plan_query(logical: L.LogicalPlan, conf: rc.RapidsConf
               ) -> Tuple[PhysicalPlan, PlanMeta]:
    phys, meta = TpuOverrides(conf).apply(logical)
    from spark_rapids_tpu.plan.broadcast_reuse import (
        dedup_broadcast_builds,
    )

    dedup_broadcast_builds(phys)
    return phys, meta
