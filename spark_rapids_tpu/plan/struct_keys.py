"""Struct grouping/join keys by canonical expansion to primitive child
key columns (round-4 verdict item #4; the reference supports nested
join/grouping keys natively in cuDF — GpuHashJoin.scala:403,
GpuOverrides' nested-key TypeSigs — where this engine's device keys
must be orderable primitive columns).

Semantics encoded by the expansion:

- **Top-level struct nullability**: a `NullGate(s)` boolean column that
  is null exactly where the struct is null. Join keys: the engine never
  matches null keys, so a null struct joins nothing (Spark EqualTo null
  propagation). Grouping keys: the engine groups nulls together, so
  null structs form one group, distinct from any non-null struct.
- **Field equality inside a non-null struct is NULL-SAFE** (Spark
  compares structs with an ordering where null == null):
  - grouping: the raw field columns already group null with null —
    expand to `GetStructField` columns directly;
  - join: the engine's probe drops null keys, so each field expands to
    the pair (`IsNull(f)`, `coalesce(f, zero)`) — both non-null — which
    matches iff the fields are both null or equal.
- Nested structs recurse (their own top-level null becomes an
  `IsNull` marker column: inside a non-null parent, null child structs
  compare EQUAL, unlike the outermost level).

Aggregate output still contains the struct key column: the rewrite
wraps the Aggregate in a Project that rebuilds it with
`CreateNamedStruct(fields, valid_from=gate)`.

Structs containing arrays/maps/128-bit decimals stay unexpanded and
keep the planner's CPU fallback (plan/typesig.py key_type_supported).
"""

from __future__ import annotations

import copy
from typing import List, Optional

from spark_rapids_tpu.expr import Alias, BoundReference
from spark_rapids_tpu.expr.core import Expression, Literal
from spark_rapids_tpu.plan import logical as L
from spark_rapids_tpu.sqltypes import DecimalType, StringType, StructType


def _zero_literal(dt) -> Optional[Literal]:
    """A non-null literal of dt for null-safe coalescing, or None when
    the type has no safe zero (those structs stay on the CPU path)."""
    import numpy as np

    if isinstance(dt, StringType):
        return Literal("", dt)
    if isinstance(dt, DecimalType):
        return None
    np_dt = getattr(dt, "np_dtype", None)
    if np_dt is None:
        return None
    if np.issubdtype(np.dtype(np_dt), np.bool_):
        return Literal(False, dt)
    if np.issubdtype(np.dtype(np_dt), np.integer):
        return Literal(0, dt)
    if np.issubdtype(np.dtype(np_dt), np.floating):
        return Literal(0.0, dt)
    return None


def _group_expandable(dt) -> bool:
    from spark_rapids_tpu.plan.typesig import key_type_supported

    if isinstance(dt, StructType):
        return all(_group_expandable(f.dataType) for f in dt.fields)
    return key_type_supported(dt) is None


def _join_expandable(dt) -> bool:
    if isinstance(dt, StructType):
        return all(_join_expandable(f.dataType) for f in dt.fields)
    return _group_expandable(dt) and _zero_literal(dt) is not None


def _fields_of(e: Expression) -> List[Expression]:
    from spark_rapids_tpu.expr.structs import GetStructField

    return [GetStructField(e, f.name) for f in e.dtype.fields]


def expand_group_key(e: Expression) -> List[Expression]:
    """Struct key -> [NullGate, field columns...] (recursing into
    struct fields with IsNull markers for their own null level)."""
    from spark_rapids_tpu.expr.predicates import IsNull
    from spark_rapids_tpu.expr.structs import NullGate

    def fields(s: Expression) -> List[Expression]:
        out: List[Expression] = []
        for g in _fields_of(s):
            if isinstance(g.dtype, StructType):
                out.append(IsNull(g))
                out.extend(fields(g))
            else:
                out.append(g)
        return out

    return [NullGate(e)] + fields(e)


def expand_join_key(e: Expression) -> List[Expression]:
    """Struct key -> [NullGate, (IsNull, coalesce(zero)) per leaf
    field] — all columns non-null below the top level, so the engine's
    null-keys-never-match probe realizes Spark's null-safe FIELD
    equality while the gate keeps top-level null propagation."""
    from spark_rapids_tpu.expr.conditional import Coalesce
    from spark_rapids_tpu.expr.predicates import IsNull
    from spark_rapids_tpu.expr.structs import NullGate

    def fields(s: Expression) -> List[Expression]:
        out: List[Expression] = []
        for g in _fields_of(s):
            if isinstance(g.dtype, StructType):
                out.append(IsNull(g))
                out.extend(fields(g))
            else:
                out.append(IsNull(g))
                out.append(Coalesce(g, _zero_literal(g.dtype)))
        return out

    return [NullGate(e)] + fields(e)


# ------------------------------------------------------------ rewrites

def _rewrite_join(plan: L.Join) -> L.LogicalPlan:
    if not any(isinstance(k.dtype, StructType) for k in plan.left_keys):
        return plan
    lks: List[Expression] = []
    rks: List[Expression] = []
    for lk, rk in zip(plan.left_keys, plan.right_keys):
        if (isinstance(lk.dtype, StructType)
                and _join_expandable(lk.dtype)):
            lks.extend(expand_join_key(lk))
            rks.extend(expand_join_key(rk))
        else:
            lks.append(lk)
            rks.append(rk)
    node = copy.copy(plan)
    node.left_keys = lks
    node.right_keys = rks
    return node


def _bound(pos: int, e: Expression) -> BoundReference:
    return BoundReference(pos, e.dtype, e.nullable)


def _rebuild_struct(dt: StructType, cols) -> Expression:
    """Reconstruct a struct value from the flat (position, expr) stream
    of its expand_group_key field columns (`cols` is an iterator of
    BoundReferences in expansion order, gate excluded)."""
    from spark_rapids_tpu.expr.structs import CreateNamedStruct

    fields: List[Expression] = []
    for f in dt.fields:
        if isinstance(f.dataType, StructType):
            marker = next(cols)  # the IsNull marker column
            sub = _rebuild_struct(f.dataType, cols)
            fields.append(_Masked(sub, marker))
        else:
            fields.append(next(cols))
    return CreateNamedStruct([f.name for f in dt.fields], fields)


def _rewrite_aggregate(plan: L.Aggregate) -> L.LogicalPlan:
    if not any(isinstance(g.dtype, StructType)
               and _group_expandable(g.dtype) for g in plan.grouping):
        return plan
    from spark_rapids_tpu.expr.structs import CreateNamedStruct

    child = plan.children[0]
    base = [Alias(BoundReference(i, f.dataType, f.nullable), f.name)
            for i, f in enumerate(child.schema.fields)]
    extra: List[Alias] = []          # expanded key columns (lower)
    grouping2: List[Alias] = []      # grouping over the lower Project
    upper: List[Alias] = []          # upper Project: grouping outputs
    n0 = len(base)

    for gi, g in enumerate(plan.grouping):
        if isinstance(g.dtype, StructType) and _group_expandable(g.dtype):
            exps = expand_group_key(g.children[0])
            gpos = len(grouping2)  # position in the agg output schema
            for j, e in enumerate(exps):
                name = f"__gk{gi}_{j}"
                pos = n0 + len(extra)
                extra.append(Alias(e, name))
                grouping2.append(Alias(_bound(pos, e), name))
            gate_ref = _bound(gpos, exps[0])
            col_refs = iter(_bound(gpos + 1 + j, e)
                            for j, e in enumerate(exps[1:]))
            inner = _rebuild_struct(g.dtype, col_refs)
            rebuilt = CreateNamedStruct(
                [f.name for f in g.dtype.fields], list(inner.children),
                valid_from=gate_ref)
            upper.append(Alias(rebuilt, g.name))
        else:
            pos = len(grouping2)
            grouping2.append(g)  # child-bound; lower keeps the prefix
            upper.append(Alias(_bound(pos, g), g.name))

    lower = L.Project(base + extra, child)
    agg2 = L.Aggregate(grouping2, plan.aggregates, lower)
    na = len(grouping2)
    for ai, a in enumerate(plan.aggregates):
        upper.append(Alias(BoundReference(
            na + ai, a.dtype, a.children[0].nullable), a.name))
    return L.Project(upper, agg2)


class _Masked(Expression):
    """value with validity ANDed from NOT(marker) — rebuilds a nested
    struct field whose own nullability was carried by an IsNull marker
    column in the expansion."""

    def __init__(self, value: Expression, marker: Expression):
        super().__init__([value, marker])

    @property
    def dtype(self):
        return self.children[0].dtype

    @property
    def nullable(self):
        return True

    def key(self):
        return ("masked", tuple(c.key() for c in self.children))

    def eval(self, ctx):
        v = self.children[0].eval(ctx)
        m = self.children[1].eval(ctx)
        # marker True (field was null) -> invalid
        return v.with_validity(v.validity & ~(m.data & m.validity))

    def __repr__(self):
        return f"masked({self.children[0]!r})"


def expand_struct_keys(plan: L.LogicalPlan) -> L.LogicalPlan:
    if isinstance(plan, L.Join):
        return _rewrite_join(plan)
    if isinstance(plan, L.Aggregate):
        return _rewrite_aggregate(plan)
    return plan
