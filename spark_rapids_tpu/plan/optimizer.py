"""Logical optimizations: scan column pruning + parquet predicate
pushdown (the reference gets these from Spark's optimizer + its own
row-group filtering, GpuParquetScan.scala:556; standalone we run a small
rewrite pass before physical planning).
"""

from __future__ import annotations

import copy
from typing import List, Optional, Tuple

from spark_rapids_tpu.expr import (
    BoundReference,
    EqualTo,
    GreaterThan,
    GreaterThanOrEqual,
    LessThan,
    LessThanOrEqual,
    Literal,
)
from spark_rapids_tpu.expr.core import Expression
from spark_rapids_tpu.plan import logical as L
from spark_rapids_tpu.sqltypes import StructType

_CMP_OPS = {EqualTo: "=", LessThan: "<", LessThanOrEqual: "<=",
            GreaterThan: ">", GreaterThanOrEqual: ">="}
_FLIP = {"=": "=", "<": ">", "<=": ">=", ">": "<", ">=": "<="}


def optimize(plan: L.LogicalPlan) -> L.LogicalPlan:
    from spark_rapids_tpu.plan.struct_keys import expand_struct_keys

    new_children = [optimize(c) for c in plan.children]
    plan = _with_children(plan, new_children)
    plan = expand_struct_keys(plan)
    plan = _push_filters(plan)
    plan = _prune_scan_columns(plan)
    return plan


def _with_children(plan: L.LogicalPlan, children) -> L.LogicalPlan:
    if all(a is b for a, b in zip(plan.children, children)) and \
            len(plan.children) == len(children):
        return plan
    node = copy.copy(plan)
    node.children = list(children)
    return node


# ------------------------------------------------- predicate pushdown

def _split_conjuncts(e: Expression) -> List[Expression]:
    from spark_rapids_tpu.expr import And

    if isinstance(e, And):
        return (_split_conjuncts(e.children[0]) +
                _split_conjuncts(e.children[1]))
    return [e]


def _filter_tuple(e: Expression, schema: StructType
                  ) -> Optional[Tuple[str, str, object]]:
    """BoundReference <cmp> Literal -> a pyarrow filter tuple. SQL
    comparisons are null-rejecting, matching pyarrow filter semantics,
    so pushdown never changes results."""
    op = _CMP_OPS.get(type(e))
    if op is None:
        return None
    a, b = e.children
    if isinstance(a, BoundReference) and isinstance(b, Literal):
        if b.value is None:
            return None
        return (schema.names[a.ordinal], op, b.value)
    if isinstance(b, BoundReference) and isinstance(a, Literal):
        if a.value is None:
            return None
        return (schema.names[b.ordinal], _FLIP[op], a.value)
    return None


def _push_filters(plan: L.LogicalPlan) -> L.LogicalPlan:
    if not (isinstance(plan, L.Filter) and
            isinstance(plan.children[0], L.FileScan) and
            plan.children[0].fmt == "parquet"):
        return plan
    scan: L.FileScan = plan.children[0]
    tuples = []
    for conj in _split_conjuncts(plan.condition):
        t = _filter_tuple(conj, scan.schema)
        if t is not None:
            tuples.append(t)
    if not tuples:
        return plan
    new_scan = copy.copy(scan)
    new_scan.pushed_filters = (getattr(scan, "pushed_filters", None) or
                               []) + tuples
    # the Filter stays (pushdown is row-group pruning, not exact)
    return _with_children(plan, [new_scan])


# --------------------------------------------------- column pruning

def _remap(e: Expression, mapping) -> Expression:
    def fn(node):
        if isinstance(node, BoundReference):
            return BoundReference(mapping[node.ordinal], node.dtype,
                                  node.nullable)
        return node

    return e.transform(fn)


def _prune(scan: L.FileScan, needed: List[int]):
    """-> (new_scan, old_ordinal -> new_ordinal) or None if no gain."""
    if scan.fmt == "hivetext":
        # positional headerless format: the parser needs the full file
        # schema (every line carries every field anyway)
        return None
    if len(needed) >= len(scan.schema.fields) or not needed:
        return None
    fields = [scan.schema.fields[i] for i in sorted(needed)]
    new_scan = copy.copy(scan)
    new_scan._schema = StructType(fields)
    mapping = {old: new for new, old in enumerate(sorted(needed))}
    return new_scan, mapping


def _prune_scan_columns(plan: L.LogicalPlan) -> L.LogicalPlan:
    # Project/Aggregate over (optional Filter over) FileScan
    if isinstance(plan, L.Project):
        top_exprs = plan.exprs
    elif isinstance(plan, L.Aggregate):
        top_exprs = plan.grouping + plan.aggregates
    else:
        return plan
    child = plan.children[0]
    filt: Optional[L.Filter] = None
    if isinstance(child, L.Filter) and isinstance(child.children[0],
                                                  L.FileScan):
        filt = child
        scan = child.children[0]
    elif isinstance(child, L.FileScan):
        scan = child
    else:
        return plan
    needed = set()
    for e in top_exprs:
        needed.update(e.references())
    if filt is not None:
        needed.update(filt.condition.references())
    pruned = _prune(scan, sorted(needed))
    if pruned is None:
        return plan
    new_scan, mapping = pruned
    bottom: L.LogicalPlan = new_scan
    if filt is not None:
        bottom = L.Filter(_remap(filt.condition, mapping), new_scan)
    if isinstance(plan, L.Project):
        return L.Project([_remap(e, mapping) for e in plan.exprs],
                         bottom)
    return L.Aggregate([_remap(g, mapping) for g in plan.grouping],
                       [_remap(a, mapping) for a in plan.aggregates],
                       bottom)
