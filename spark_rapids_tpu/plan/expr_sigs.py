"""Per-parameter type signatures — the TypeSig algebra
(reference TypeChecks.scala:168 `TypeSig`, `ExprChecks` at :757).

Each device expression declares which input types its device lowering
accepts, per parameter. The planner's type-check walk
(plan/typesig.py `expr_unsupported_reasons`) enforces these — a
mismatch tags the expression NOT_ON_TPU with a per-parameter reason,
exactly like the reference's ExprChecks tagging — and
tools/gendocs.py renders the registry as the supported_ops matrix.

Signatures describe the CURRENT device lowerings (ops/ + expr/
device paths); the per-class `register_check` refinements in
plan/typesig.py still layer on top for value-dependent restrictions
(regex dialect, ANSI-failable casts, decimal-128 arithmetic corners).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple, Type

from spark_rapids_tpu.sqltypes import (
    ArrayType,
    BooleanType,
    DataType,
    DateType,
    DecimalType,
    DoubleType,
    FloatType,
    IntegralType,
    MapType,
    NullType,
    StringType,
    StructType,
    TimestampType,
)

# ------------------------------------------------------------- algebra

_KINDS = ("boolean", "integral", "float", "double", "decimal64",
          "decimal128", "string", "date", "timestamp", "null", "array",
          "map", "struct")


def kind_of(dt: DataType) -> str:
    if isinstance(dt, BooleanType):
        return "boolean"
    if isinstance(dt, IntegralType):
        return "integral"
    if isinstance(dt, FloatType):
        return "float"
    if isinstance(dt, DoubleType):
        return "double"
    if isinstance(dt, DecimalType):
        return ("decimal128"
                if dt.precision > DecimalType.MAX_LONG_DIGITS
                else "decimal64")
    if isinstance(dt, StringType):
        return "string"
    if isinstance(dt, DateType):
        return "date"
    if isinstance(dt, TimestampType):
        return "timestamp"
    if isinstance(dt, NullType):
        return "null"
    if isinstance(dt, ArrayType):
        return "array"
    if isinstance(dt, MapType):
        return "map"
    if isinstance(dt, StructType):
        return "struct"
    return "unsupported"


class TypeSig:
    """An accepted set of type kinds (TypeSig algebra: compose with +)."""

    __slots__ = ("kinds",)

    def __init__(self, *kinds: str):
        for k in kinds:
            assert k in _KINDS, k
        self.kinds = frozenset(kinds)

    def __add__(self, other: "TypeSig") -> "TypeSig":
        s = TypeSig()
        s.kinds = self.kinds | other.kinds
        return s

    def supports(self, dt: DataType) -> Optional[str]:
        k = kind_of(dt)
        if k == "null":
            return None  # null literals coerce everywhere
        if k in self.kinds:
            return None
        return k

    def __contains__(self, kind: str) -> bool:
        return kind in self.kinds


BOOL = TypeSig("boolean")
INTEGRAL = TypeSig("integral")
FP = TypeSig("float", "double")
DECIMAL = TypeSig("decimal64", "decimal128")
DECIMAL_64 = TypeSig("decimal64")
NUMERIC = INTEGRAL + FP + DECIMAL
STRING = TypeSig("string")
DATE = TypeSig("date")
TIMESTAMP = TypeSig("timestamp")
DATETIME = DATE + TIMESTAMP
ORDERABLE = NUMERIC + STRING + DATETIME + BOOL
ARRAY = TypeSig("array")
MAP = TypeSig("map")
STRUCT = TypeSig("struct")
COMMON = ORDERABLE  # the scalar device surface
# ALL deliberately EXCLUDES struct: the conditional/null lowerings it
# gates (If/Coalesce/Nvl2/CaseWhen) rebuild columns without the
# children leaf; ops that do handle structs name STRUCT explicitly
ALL = COMMON + ARRAY + MAP
ALL_NESTED = ALL + STRUCT


class ExprSig:
    """Per-parameter signature: positional param sigs, an optional
    variadic sig covering remaining children, and the result sig."""

    __slots__ = ("params", "variadic", "result", "note")

    def __init__(self, params: Sequence[Tuple[str, TypeSig]],
                 result: TypeSig,
                 variadic: Optional[Tuple[str, TypeSig]] = None,
                 note: str = ""):
        self.params = list(params)
        self.variadic = variadic
        self.result = result
        self.note = note

    def check(self, expr) -> List[str]:
        reasons = []
        name = type(expr).__name__
        for i, child in enumerate(expr.children):
            if i < len(self.params):
                pname, sig = self.params[i]
            elif self.variadic is not None:
                pname, sig = self.variadic
            else:
                continue
            if child is None:
                continue
            bad = sig.supports(child.dtype)
            if bad:
                reasons.append(
                    f"{name} parameter {pname!r}: {bad} input has no "
                    "device lowering")
        bad = self.result.supports(expr.dtype)
        if bad:
            reasons.append(
                f"{name}: {bad} output has no device lowering")
        return reasons


# ------------------------------------------------------------ registry
#
# Built lazily (expression modules import broadly); class -> ExprSig.

_SIGS: Optional[Dict[Type, ExprSig]] = None


def _u(name: str, params, result, variadic=None, note=""):
    return name, ExprSig(params, result, variadic, note)


def _build() -> Dict[Type, ExprSig]:
    from spark_rapids_tpu.expr import (
        arith as A,
        conditional as C,
        datetimes as D,
        hashexpr as H,
        mathexpr as M,
        predicates as P,
        strings as S,
    )
    from spark_rapids_tpu.expr import generators as G
    from spark_rapids_tpu.expr import regexexpr as R
    from spark_rapids_tpu.expr import structs as ST

    num2 = [("lhs", NUMERIC), ("rhs", NUMERIC)]
    ord2 = [("lhs", ORDERABLE), ("rhs", ORDERABLE)]
    str1 = [("str", STRING)]
    sigs: Dict[Type, ExprSig] = {
        # arithmetic (reference org/apache/spark/sql/rapids/arithmetic)
        A.Add: ExprSig(num2, NUMERIC),
        A.Subtract: ExprSig(num2, NUMERIC),
        A.Multiply: ExprSig(num2, NUMERIC),
        A.Divide: ExprSig(num2, FP + DECIMAL),
        A.IntegralDivide: ExprSig(num2, INTEGRAL),
        A.Remainder: ExprSig(num2, NUMERIC),
        A.Pmod: ExprSig(num2, NUMERIC),
        A.UnaryMinus: ExprSig([("input", NUMERIC)], NUMERIC),
        A.Abs: ExprSig([("input", NUMERIC)], NUMERIC),
        # predicates
        P.EqualTo: ExprSig(ord2, BOOL),
        P.EqualNullSafe: ExprSig(ord2, BOOL),
        P.LessThan: ExprSig(ord2, BOOL),
        P.GreaterThan: ExprSig(ord2, BOOL),
        P.LessThanOrEqual: ExprSig(ord2, BOOL),
        P.GreaterThanOrEqual: ExprSig(ord2, BOOL),
        P.And: ExprSig([("lhs", BOOL), ("rhs", BOOL)], BOOL),
        P.Or: ExprSig([("lhs", BOOL), ("rhs", BOOL)], BOOL),
        P.Not: ExprSig([("input", BOOL)], BOOL),
        P.IsNull: ExprSig([("input", ALL_NESTED)], BOOL),
        P.IsNotNull: ExprSig([("input", ALL_NESTED)], BOOL),
        P.IsNaN: ExprSig([("input", FP)], BOOL),
        P.In: ExprSig([("value", ORDERABLE)], BOOL,
                      variadic=("list", ORDERABLE)),
        # strings (device byte-matrix kernels, ops/ + expr/strings.py).
        # Sigs describe CHILD expressions only — scalar arguments
        # (search/pad/format strings, positions) are constructor
        # attributes in this engine, enforced at construction.
        S.Length: ExprSig(str1, INTEGRAL),
        S.Upper: ExprSig(str1, STRING,
                         note="ASCII case map (docs/compatibility.md)"),
        S.Lower: ExprSig(str1, STRING,
                         note="ASCII case map (docs/compatibility.md)"),
        S.Substring: ExprSig(str1, STRING),
        S.Concat: ExprSig([], STRING, variadic=("str", STRING)),
        S.StartsWith: ExprSig(str1, BOOL),
        S.EndsWith: ExprSig(str1, BOOL),
        S.Contains: ExprSig(str1, BOOL),
        S.StringTrim: ExprSig(str1, STRING),
        S.StringTrimLeft: ExprSig(str1, STRING),
        S.StringTrimRight: ExprSig(str1, STRING),
        S.StringLPad: ExprSig(str1, STRING),
        S.StringRPad: ExprSig(str1, STRING),
        S.StringRepeat: ExprSig(str1, STRING),
        S.StringReverse: ExprSig(str1, STRING),
        S.InitCap: ExprSig(str1, STRING),
        S.StringInstr: ExprSig(str1, INTEGRAL),
        S.StringLocate: ExprSig(str1, INTEGRAL),
        S.StringTranslate: ExprSig(str1, STRING),
        S.StringReplace: ExprSig(str1, STRING),
        S.ConcatWs: ExprSig([], STRING, variadic=("str", STRING)),
        S.Ascii: ExprSig(str1, INTEGRAL),
        S.Chr: ExprSig([("n", INTEGRAL)], STRING),
        S.SubstringIndex: ExprSig(str1, STRING),
        # datetime (device tz database, ops/tzdb.py). The date-part
        # lowerings accept timestamps too (_days_of converts); format/
        # zone/unit arguments are constructor attributes.
        D.DateAdd: ExprSig([("start", DATE), ("days", INTEGRAL)], DATE),
        D.DateSub: ExprSig([("start", DATE), ("days", INTEGRAL)], DATE),
        D.DateDiff: ExprSig([("end", DATETIME), ("start", DATETIME)],
                            INTEGRAL),
        D.AddMonths: ExprSig([("start", DATE), ("months", INTEGRAL)],
                             DATE),
        D.MonthsBetween: ExprSig(
            [("end", DATETIME), ("start", DATETIME)], FP),
        D.NextDay: ExprSig([("start", DATE)], DATE),
        D.LastDay: ExprSig([("input", DATETIME)], DATE),
        D.TruncDate: ExprSig([("date", DATE)], DATE),
        D.DateTrunc: ExprSig([("ts", TIMESTAMP)], TIMESTAMP),
        D.UnixTimestamp: ExprSig([("time", DATETIME)], INTEGRAL),
        D.SecondsToTimestamp: ExprSig([("secs", NUMERIC)], TIMESTAMP),
        D.MakeDate: ExprSig(
            [("year", INTEGRAL), ("month", INTEGRAL),
             ("day", INTEGRAL)], DATE),
        D.FromUtcTimestamp: ExprSig([("ts", TIMESTAMP)], TIMESTAMP),
        D.ToUtcTimestamp: ExprSig([("ts", TIMESTAMP)], TIMESTAMP),
        # FromUnixtime wraps its input as SecondsToTimestamp at
        # construction, so the single child is already a timestamp
        D.FromUnixtime: ExprSig([("time", TIMESTAMP)], STRING),
        D.DateFormat: ExprSig([("ts", DATETIME)], STRING),
        # math (elementwise XLA; inputs promote to double)
        M.Pow: ExprSig([("lhs", NUMERIC), ("rhs", NUMERIC)], FP),
        M.Atan2: ExprSig([("y", NUMERIC), ("x", NUMERIC)], FP),
        M.Hypot: ExprSig([("x", NUMERIC), ("y", NUMERIC)], FP),
        M.Logarithm: ExprSig([("base", NUMERIC), ("x", NUMERIC)], FP),
        M.Round: ExprSig([("x", NUMERIC), ("scale", INTEGRAL)],
                         NUMERIC),
        M.BRound: ExprSig([("x", NUMERIC), ("scale", INTEGRAL)],
                          NUMERIC),
        M.Ceil: ExprSig([("x", NUMERIC)], NUMERIC),
        M.Floor: ExprSig([("x", NUMERIC)], NUMERIC),
        M.BitwiseAnd: ExprSig([("lhs", INTEGRAL), ("rhs", INTEGRAL)],
                              INTEGRAL),
        M.BitwiseOr: ExprSig([("lhs", INTEGRAL), ("rhs", INTEGRAL)],
                             INTEGRAL),
        M.BitwiseXor: ExprSig([("lhs", INTEGRAL), ("rhs", INTEGRAL)],
                              INTEGRAL),
        M.BitwiseNot: ExprSig([("input", INTEGRAL)], INTEGRAL),
        M.ShiftLeft: ExprSig([("value", INTEGRAL), ("bits", INTEGRAL)],
                             INTEGRAL),
        M.ShiftRight: ExprSig([("value", INTEGRAL), ("bits", INTEGRAL)],
                              INTEGRAL),
        M.ShiftRightUnsigned: ExprSig(
            [("value", INTEGRAL), ("bits", INTEGRAL)], INTEGRAL),
        M.Hex: ExprSig([("input", INTEGRAL)], STRING),
        # conditionals
        C.If: ExprSig([("predicate", BOOL), ("then", ALL),
                       ("else", ALL)], ALL),
        C.CaseWhen: ExprSig([], ALL, variadic=("input", ALL)),
        C.Coalesce: ExprSig([], ALL, variadic=("input", ALL)),
        C.Greatest: ExprSig([], ORDERABLE,
                            variadic=("input", ORDERABLE)),
        C.Least: ExprSig([], ORDERABLE, variadic=("input", ORDERABLE)),
        C.Nvl2: ExprSig([("test", ALL), ("notNull", ALL),
                         ("isNull", ALL)], ALL),
        C.NaNvl: ExprSig([("x", FP), ("fallback", FP)], FP),
        # hash (Spark-exact murmur3/xxhash64 on device, ops/hashing.py)
        H.Murmur3Hash: ExprSig([], INTEGRAL,
                               variadic=("input", COMMON)),
        H.XxHash64: ExprSig([], INTEGRAL, variadic=("input", COMMON)),
        # regex (device DFA; dialect limits layered by register_check)
        R.RLike: ExprSig([("str", STRING)], BOOL),
        R.RegexpExtract: ExprSig([("str", STRING)], STRING),
        R.RegexpReplace: ExprSig([("str", STRING)], STRING),
        # generators (map explode has no lowering here)
        G.Explode: ExprSig([("input", ARRAY)], ALL),
        G.PosExplode: ExprSig([("input", ARRAY)], ALL),
        # structs (expr/structs.py; struct-of-arrays device columns)
        ST.GetStructField: ExprSig([("struct", STRUCT)], ALL),
        ST.CreateNamedStruct: ExprSig([], STRUCT,
                                      variadic=("field", COMMON)),
    }
    # elementwise unary double-domain math: one shared signature
    for cls in (M.Sqrt, M.Exp, M.Expm1, M.Cbrt, M.Rint, M.Signum,
                M.Sin, M.Cos, M.Tan, M.Cot, M.Asin, M.Acos, M.Atan,
                M.Sinh, M.Cosh, M.Tanh, M.Asinh, M.Acosh, M.Atanh,
                M.ToDegrees, M.ToRadians, M.Log, M.Log10, M.Log2,
                M.Log1p):
        sigs[cls] = ExprSig([("input", NUMERIC)], FP)
    # date-part extractors: the device lowering converts timestamps to
    # local days itself (_days_of), so both kinds are in
    for cls in (D.Year, D.Month, D.DayOfMonth, D.DayOfWeek, D.WeekDay,
                D.DayOfYear, D.WeekOfYear, D.Quarter):
        sigs[cls] = ExprSig([("input", DATETIME)], INTEGRAL)
    for cls in (D.Hour, D.Minute, D.Second):
        sigs[cls] = ExprSig([("input", TIMESTAMP)], INTEGRAL)
    return sigs


def signatures() -> Dict[Type, ExprSig]:
    global _SIGS
    if _SIGS is None:
        _SIGS = _build()
    return _SIGS


def check_expr(expr) -> List[str]:
    sig = signatures().get(type(expr))
    if sig is None:
        return []
    return sig.check(expr)
