"""Device-support checks — the TypeSig/TypeChecks analog.

The reference's `TypeSig` algebra (`TypeChecks.scala:168,543`) declares,
per operator and per parameter, which Spark types run on device, and
produces tagging reasons + docs/supported_ops.md. This is the same idea
sized for the v1 surface: a per-expression-class registry of checks that
return a reason string when something must fall back to CPU.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Type

from spark_rapids_tpu.config import rapids_conf as rc_mod
from spark_rapids_tpu.expr import Cast
from spark_rapids_tpu.expr.core import Expression, Literal
from spark_rapids_tpu.sqltypes import (
    BooleanType,
    DataType,
    DateType,
    DecimalType,
    DoubleType,
    FloatType,
    IntegralType,
    NullType,
    StringType,
    TimestampType,
)

DEVICE_TYPES = (BooleanType, IntegralType, FloatType, DoubleType,
                StringType, DateType, TimestampType, DecimalType)


def _wide_dec(dt: DataType) -> bool:
    return isinstance(dt, DecimalType) and \
        dt.precision > DecimalType.MAX_LONG_DIGITS


def type_supported(dt: DataType) -> Optional[str]:
    from spark_rapids_tpu.sqltypes import ArrayType, StructType

    if isinstance(dt, NullType):
        return None
    from spark_rapids_tpu.sqltypes import MapType as _MT

    if isinstance(dt, ArrayType):
        et = dt.elementType
        if isinstance(et, StringType):
            return None  # [cap, elems, bytes] cube (arrow_bridge)
        if isinstance(et, (ArrayType, StructType, _MT)) \
                or _wide_dec(et):
            return (f"array element type {et.simpleString} runs on CPU "
                    "(device arrays hold primitive/string elements "
                    "in v1)")
        return type_supported(et)
    if isinstance(dt, _MT):
        for part, t in (("key", dt.keyType), ("value", dt.valueType)):
            if isinstance(t, (StringType, ArrayType, _MT, StructType)) \
                    or _wide_dec(t):
                return (f"map {part} type {t.simpleString} runs on CPU "
                        "(device maps hold primitive/64-bit entries "
                        "in v1)")
            r = type_supported(t)
            if r:
                return r
        return None
    if isinstance(dt, StructType):
        # struct-of-arrays device columns (DeviceColumn.children):
        # primitive/string fields; nested structs stay CPU in v1
        for f in dt.fields:
            if isinstance(f.dataType, (ArrayType, _MT, StructType)):
                return (f"struct field {f.name!r} type "
                        f"{f.dataType.simpleString} runs on CPU "
                        "(device structs hold flat fields in v1)")
            r = type_supported(f.dataType)
            if r:
                return r
        return None
    if not isinstance(dt, DEVICE_TYPES):
        return f"type {dt} not supported on device"
    return None


def key_type_supported(dt: DataType) -> Optional[str]:
    """Grouping/join/sort keys additionally need orderable device keys;
    arrays/structs have no orderable-key lowering yet."""
    from spark_rapids_tpu.sqltypes import ArrayType, StructType

    if isinstance(dt, ArrayType):
        return "array-typed keys run on CPU (no orderable device keys)"
    if isinstance(dt, StructType):
        return "struct-typed keys run on CPU (no orderable device keys)"
    from spark_rapids_tpu.sqltypes import MapType as _MT2

    if isinstance(dt, _MT2):
        return "map-typed keys run on CPU (maps are not orderable)"
    if _wide_dec(dt):
        # the SHUFFLE hash of a >18-digit decimal needs Spark's
        # minimal-two's-complement-byte murmur3, not lowered yet
        return ("decimal(>18) grouping/join keys run on CPU "
                "(no device hash for 128-bit keys in v1)")
    return type_supported(dt)


_checks: Dict[Type[Expression], Callable[[Expression], Optional[str]]] = {}


def register_check(cls):
    def deco(fn):
        _checks[cls] = fn
        return fn
    return deco


@register_check(Cast)
def _cast_check(e: Cast) -> Optional[str]:
    if not e.device_supported():
        return (f"cast {e.children[0].dtype.simpleString} -> "
                f"{e.to.simpleString} runs on CPU in v1")
    from spark_rapids_tpu.config.rapids_conf import ansi_enabled

    if ansi_enabled() and e.can_fail():
        # numeric narrowing / float->int casts raise on DEVICE via the
        # compiled overflow-mask check (expr/ansicheck.py); only casts
        # without a device check (string parses, decimal) fall back
        from spark_rapids_tpu.expr.ansicheck import _node_checked

        if not _node_checked(e):
            return (f"ANSI mode: failable cast "
                    f"{e.children[0].dtype.simpleString} -> "
                    f"{e.to.simpleString} runs on CPU so errors raise "
                    "eagerly")
    return None


def expr_unsupported_reasons(expr: Expression,
                             conf=None) -> List[str]:
    """Walk an expression tree; collect every reason it cannot run on
    device. Empty list == fully supported. `conf` (the planning
    session's RapidsConf) carries the per-expression disable switches;
    None falls back to the active session's conf."""
    reasons: List[str] = []
    if conf is None:
        from spark_rapids_tpu.api.session import TpuSparkSession

        s = TpuSparkSession.active()
        conf = s.rapids_conf if s is not None else None

    from spark_rapids_tpu.expr.aggregates import AggregateFunction
    from spark_rapids_tpu.expr.windows import (
        WindowExpression,
        WindowFunction,
    )

    operator_evaluated = (AggregateFunction, WindowFunction,
                          WindowExpression)

    def walk(e: Expression):
        name = type(e).__name__
        if conf is not None and not conf.expression_enabled(name):
            reasons.append(
                f"{name} disabled via spark.rapids.sql.expression."
                f"{name}=false")
        if conf is not None and not conf.get(rc_mod.REGEXP_ENABLED):
            from spark_rapids_tpu.expr.regexexpr import (
                RegexpExtract,
                RegexpReplace,
                RLike,
            )

            if isinstance(e, (RLike, RegexpExtract, RegexpReplace)):
                reasons.append(
                    "regex on device disabled via "
                    "spark.rapids.sql.regexp.enabled=false")
        r = type_supported(e.dtype)
        if r:
            reasons.append(f"{type(e).__name__}: {r}")
        # per-parameter TypeSig enforcement (plan/expr_sigs.py, the
        # ExprChecks role)
        from spark_rapids_tpu.plan.expr_sigs import check_expr

        reasons.extend(check_expr(e))
        chk = _checks.get(type(e))
        if chk:
            r = chk(e)
            if r:
                reasons.append(r)
        if (type(e).eval is Expression.eval and not isinstance(e, Literal)
                and not isinstance(e, operator_evaluated)):
            reasons.append(
                f"{type(e).__name__} has no device implementation")
        if isinstance(e, AggregateFunction):
            from spark_rapids_tpu.sqltypes import StructType as _StT

            for c in e.children:
                if c is not None and isinstance(c.dtype, _StT):
                    reasons.append(
                        f"{name} over struct input runs on CPU "
                        "(segmented kernels take flat columns)")
                if c is not None and _is_cube(c.dtype):
                    reasons.append(
                        f"{name} over array<string> runs on CPU "
                        "(no 3-D cube aggregation in v1)")
        for c in e.children:
            walk(c)

    walk(expr)
    return reasons


from spark_rapids_tpu.expr.regexexpr import RLike  # noqa: E402


@register_check(RLike)
def _rlike_check(e: "RLike") -> Optional[str]:
    return e.device_supported()


from spark_rapids_tpu.udf.pandas_udf import PandasUDF  # noqa: E402


@register_check(PandasUDF)
def _pandas_udf_check(e: "PandasUDF") -> Optional[str]:
    return ("pandas UDF runs via the Arrow worker-process exchange "
            "(GpuArrowEvalPythonExec role, host-side)")


from spark_rapids_tpu.expr.datetimes import DateFormat  # noqa: E402


@register_check(DateFormat)
def _date_format_check(e: "DateFormat") -> Optional[str]:
    return e.device_supported()


from spark_rapids_tpu.expr.arith import Divide, Multiply  # noqa: E402


@register_check(Divide)
def _divide_check(e) -> Optional[str]:
    if _wide_dec(e.children[0].dtype) or _wide_dec(e.children[1].dtype):
        return ("decimal(>18) division runs on CPU "
                "(128/128 device division not lowered)")
    return None


@register_check(Multiply)
def _multiply_check(e) -> Optional[str]:
    if _wide_dec(e.children[0].dtype) or _wide_dec(e.children[1].dtype):
        return ("decimal(>18) operand multiplication runs on CPU "
                "(only 64x64 -> 128 is lowered)")
    return None


def _is_cube(dt) -> bool:
    from spark_rapids_tpu.sqltypes import ArrayType

    return (isinstance(dt, ArrayType)
            and isinstance(dt.elementType, StringType))


def _register_cube_gates():
    """array<string> rides a 3-D [cap, elems, bytes] cube
    (DeviceColumn.elem_lengths); only contains/getItem/element_at/
    size/explode/select/lead-lag/serde/sort-payload paths are
    cube-aware in v1. Every other array expression falls back to CPU
    with a reason instead of crashing on the 3-D layout."""
    from spark_rapids_tpu.expr import collections as C

    def no_cube(e) -> Optional[str]:
        from spark_rapids_tpu.sqltypes import ArrayType as _AT

        if isinstance(e, Literal) and isinstance(e.dtype, _AT):
            # Literal.eval builds flat columns only — array literals
            # of ANY element type evaluate host-side
            return ("array literal runs on CPU "
                    "(no device array-literal fill in v1)")
        if any(_is_cube(c.dtype) for c in e.children) or \
                _is_cube(e.dtype):
            return (f"{type(e).__name__} over array<string> runs on "
                    "CPU (no 3-D cube lowering in v1)")
        return None

    gated = (C.ArrayTransform, C.ArrayFilter, C.ArrayMax, C.ArrayMin,
             C.SortArray, C.Slice, C.ArrayPosition, C.ArrayRemove,
             C.ArrayDistinct, C.Reverse, C.ArrayExists, C.ArrayForall,
             C.ConcatArrays, C.ArraysOverlap, C.ArrayIntersect,
             C.ArrayExcept, C.ArrayUnion, C.CreateArray,
             Literal)  # Literal.eval builds flat columns only
    for cls in gated:
        prev = _checks.get(cls)
        # CHAIN with any earlier registered check — the registry holds
        # one slot per class and must not silently clobber
        _checks[cls] = (lambda e, p=prev:
                        no_cube(e) or (p(e) if p else None))


_register_cube_gates()
