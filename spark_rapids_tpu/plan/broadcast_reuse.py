"""Broadcast-exchange reuse — the GpuBroadcastExchangeExec-reuse /
ReusedExchangeExec role (reference: broadcast builds are identified and
re-used across consumers, incl. by AQE, GpuBroadcastExchangeExec.scala;
SURVEY.md §2.5 Broadcast "re-used by AQE").

Post-planning pass: broadcast joins whose BUILD subtrees are
structurally identical share ONE child node instance, and the
materialized build caches on that instance (exec/joins.py
_BroadcastBuildMixin) — N joins against the same dimension table pay
one build and one device residency.

Keys are structural (_plan_key for interior operators) with
source-distinguishing leaves (file list for scans, table identity for
local relations). Any node without a trusted key contributes a
unique-identity term, so unknown shapes NEVER dedup — correctness over
reuse.
"""

from __future__ import annotations

from typing import Dict

from spark_rapids_tpu.exec import joins as J
from spark_rapids_tpu.exec import operators as ops
from spark_rapids_tpu.exec.base import PhysicalPlan

#: Interior operators whose _plan_key captures full semantics (their
#: parameters are part of the key, children keyed recursively here).
_SAFE_INTERIOR = (
    ops.TpuProjectExec, ops.TpuFilterExec, ops.TpuHashAggregateExec,
    ops.TpuSortExec, ops.TpuLocalLimitExec, ops.UnionExec,
    ops.TpuWindowExec, ops.TpuGenerateExec, ops.TpuExpandExec,
    ops.TpuSampleExec, ops.TpuShuffleExchangeExec, ops.ArrowToDeviceExec,
    ops.TpuCoalesceBatchesExec,
    J.TpuShuffledHashJoinExec, J.TpuBroadcastHashJoinExec,
)


def _subtree_key(n: PhysicalPlan):
    if isinstance(n, ops.TpuFileScanExec):
        from spark_rapids_tpu.runtime.jit_cache import schema_key

        own = ("scan", n.fmt,
               tuple(f for t in n._tasks for f in t),
               tuple(n.pushed_columns or ()),
               tuple(map(str, n.pushed_filters or ())),
               schema_key(n.schema),
               repr(sorted((k, repr(v))
                           for k, v in (n.options or {}).items())))
    elif isinstance(n, ops.LocalRelationExec):
        # same table OBJECT => same data; different objects never dedup
        own = ("local", id(n.table))
    elif isinstance(n, _SAFE_INTERIOR):
        from spark_rapids_tpu.parallel.plan_compiler import _plan_key

        own = _plan_key(n)[:2]
    else:
        # unknown shape: identity term — unequal to every other key
        own = object()
    return (own, tuple(_subtree_key(c) for c in n.children))


def dedup_broadcast_builds(root: PhysicalPlan) -> PhysicalPlan:
    seen: Dict[object, PhysicalPlan] = {}

    def walk(n: PhysicalPlan) -> None:
        for c in n.children:
            walk(c)
        if isinstance(n, (J.TpuBroadcastHashJoinExec,
                          J.TpuBroadcastNestedLoopJoinExec)):
            key = _subtree_key(n.children[1])
            prev = seen.get(key)
            if prev is not None and prev is not n.children[1]:
                n.children[1] = prev
            else:
                seen[key] = n.children[1]

    walk(root)
    return root
