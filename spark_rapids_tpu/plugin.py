"""Plugin lifecycle — the SQLPlugin / RapidsDriverPlugin /
RapidsExecutorPlugin surface (reference: sql-plugin-api SQLPlugin.scala,
Plugin.scala:412-684, ColumnarOverrideRules Plugin.scala:49-56).

Standalone, the session owns the process, so the "driver" and
"executor" hooks both run inside TpuSparkSession construction — but the
lifecycle is factored exactly like the reference so an embedding
framework (or a future multi-process deployment) can drive the hooks
itself:

- TpuDriverPlugin.init: validate/fix up the conf, produce the conf map
  to broadcast to executors (Plugin.scala:439-464).
- TpuExecutorPlugin.init: validate the device, initialize the memory
  pool + spill catalog, shuffle env, and semaphore
  (Plugin.scala:484-545), and install the fatal-error policy.
- ColumnarOverrideRules: the rule objects a planner integration would
  inject (pre = TpuOverrides, post = transition insertion — both are
  applied by plan_query here).
"""

from __future__ import annotations

import sys
from typing import Dict, Optional

from spark_rapids_tpu.config import rapids_conf as rc
from spark_rapids_tpu.config.rapids_conf import FATAL_ERROR_EXIT


class TpuDriverPlugin:
    """Driver-side init: conf validation + broadcastable conf map."""

    def init(self, conf: rc.RapidsConf) -> Dict[str, object]:
        unknown = getattr(conf, "unknown_keys", [])
        bad = [k for k in unknown if k.startswith("spark.rapids")]
        bad += self._unmatched_op_switches(conf)
        if bad:
            import warnings

            warnings.warn(
                f"unknown spark.rapids.* conf keys ignored: {sorted(bad)}")
        # the executor-broadcast conf map (RapidsConf.rapidsConfMap role)
        return {k: v for k, v in conf._values.items()}

    @staticmethod
    def _unmatched_op_switches(conf: rc.RapidsConf) -> list:
        """Per-operator switch keys naming no known logical operator /
        expression class — a typo'd switch must warn, not silently
        no-op (the registered-key diagnostic, extended to the dynamic
        namespace)."""
        switches = getattr(conf, "_op_switches", {})
        if not switches:
            return []
        import inspect

        import spark_rapids_tpu.expr as E
        import spark_rapids_tpu.plan.logical as L
        from spark_rapids_tpu.expr.core import Expression

        logical = {type_.__name__ for type_ in vars(L).values()
                   if inspect.isclass(type_)
                   and issubclass(type_, L.LogicalPlan)}
        import spark_rapids_tpu.expr.aggregates as _A
        import spark_rapids_tpu.expr.windows as _W
        import spark_rapids_tpu.udf.pandas_udf as _P

        exprs = {c.__name__
                 for mod in (E, _A, _W, _P)
                 for c in vars(mod).values()
                 if inspect.isclass(c) and issubclass(c, Expression)}
        bad = []
        for (kind, name) in switches:
            valid = logical if kind == "exec" else exprs
            if name not in valid:
                bad.append(f"spark.rapids.sql.{kind}.{name}")
        return bad


class TpuExecutorPlugin:
    """Executor-side init (Plugin.scala:484-545 analog)."""

    def __init__(self):
        self.initialized = False

    def init(self, conf: rc.RapidsConf):
        from spark_rapids_tpu.io import filecache
        from spark_rapids_tpu.runtime import admission, compile_cache, \
            degrade, device_monitor, faults, memory, sanitizer, semaphore
        from spark_rapids_tpu.shuffle.manager import configure_shuffle

        self._validate_device()
        # chaos registry FIRST: every later init step is itself a
        # consumer of an injection site (compile.cache_load, io.read)
        faults.configure(conf)
        degrade.configure(conf)
        # device-loss monitor before anything that can touch the
        # backend: the very first dispatch is already fatal-classified
        # and fence-recoverable (the process device epoch survives
        # reconfiguration)
        device_monitor.configure(conf)
        # query governance front door (admission queue + cancel
        # registry) — after faults so admission.slow_drain is armed
        admission.configure(conf)
        # concurrency sanitizer BEFORE the semaphore so the very first
        # acquire is already under wait-for-graph surveillance
        sanitizer.configure(conf)
        filecache.configure(conf)  # FileCache.init (Plugin.scala:545)
        # persistent compilation layer BEFORE any program compiles, so
        # the whole session (incl. warmup) rides the disk cache
        compile_cache.configure(conf)
        memory.initialize_memory(conf, force=True)
        semaphore.initialize(
            conf.get(rc.CONCURRENT_TPU_TASKS),
            conf.get(rc.SEMAPHORE_ACQUIRE_TIMEOUT_MS),
            atomic_query_groups=conf.get(
                rc.SEMAPHORE_ATOMIC_QUERY_GROUPS))
        configure_shuffle(
            conf.get(rc.SHUFFLE_MODE),
            shuffle_dir=conf.get(rc.SPILL_DIR) or None,
            num_threads=conf.get(rc.MULTITHREADED_READ_NUM_THREADS),
            codec=conf.get(rc.SHUFFLE_COMPRESSION_CODEC),
            spill_threshold=conf.get(rc.SHUFFLE_SPILL_THRESHOLD),
            checksum=conf.get(rc.SHUFFLE_CHECKSUM_ENABLED))
        self._fatal_exit_code = conf.get(FATAL_ERROR_EXIT)
        self.initialized = True

    def _validate_device(self):
        """Device/arch validation (validateGpuArchitecture role): jax
        must initialize and expose at least one device."""
        import jax

        devs = jax.devices()
        if not devs:
            raise RuntimeError("no jax devices available")

    def on_task_failed(self, exc: BaseException) -> bool:
        """Fatal-error policy (Plugin.scala:651-675): unrecoverable
        device/runtime failures optionally kill the process so the
        cluster manager reschedules. Returns True when the error is
        classified fatal."""
        fatal = _is_fatal_device_error(exc)
        if fatal and getattr(self, "_fatal_exit_code", 0):
            sys.stderr.write(
                f"fatal device error, exiting "
                f"{self._fatal_exit_code}: {exc}\n")
            sys.stderr.flush()
            sys.exit(self._fatal_exit_code)
        return fatal

    def shutdown(self):
        from spark_rapids_tpu.runtime import compile_cache, memory

        compile_cache.flush()  # drain pending index/artifact writes
        memory.shutdown_memory()


def _is_fatal_device_error(exc: BaseException) -> bool:
    """Classify unrecoverable device failures (the CudaFatalException
    analog) by delegating to the device monitor's taxonomy
    (runtime/device_monitor.py) — one classifier for the exit policy
    and the warm-recovery fence. A DeviceLostError is explicitly NOT
    process-fatal: it is the already-classified, already-being-
    recovered form, and killing the process would throw away the warm
    engine the recovery just saved."""
    from spark_rapids_tpu.runtime import device_monitor
    from spark_rapids_tpu.runtime.errors import DeviceLostError

    if isinstance(exc, DeviceLostError):
        return False
    return device_monitor.classify(exc) == "fatal"


class ColumnarOverrideRules:
    """The rule pair a planner integration injects (ColumnarOverrideRules
    Plugin.scala:49-56). `pre` tags + converts, `post` is the transition
    insertion — both run inside plan_query for the standalone engine."""

    def pre_columnar_transitions(self, conf: rc.RapidsConf):
        from spark_rapids_tpu.plan.overrides import TpuOverrides

        return TpuOverrides(conf)

    def post_columnar_transitions(self, conf: rc.RapidsConf):
        # transition insertion lives inside TpuOverrides._convert
        # (_to_device/_to_host); exposed for API parity
        return None


_executor_plugin: Optional[TpuExecutorPlugin] = None


def executor_plugin() -> TpuExecutorPlugin:
    global _executor_plugin
    if _executor_plugin is None:
        _executor_plugin = TpuExecutorPlugin()
    return _executor_plugin
