"""TpuSparkSession — the plugin lifecycle + session entry point.

Covers the responsibilities of the reference's driver/executor plugins
(`Plugin.scala:412-684`): validate the device, initialize the memory
pool/spill catalog (GpuDeviceManager.initializeGpuAndMemory), install the
semaphore with the configured concurrency, and expose conf + read/write
entry points. As a standalone engine it also owns what Spark itself would:
session state, DataFrame creation, and the reader API.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

import pyarrow as pa

from spark_rapids_tpu.config import rapids_conf as rc


class TpuSparkSessionBuilder:
    def __init__(self):
        self._conf: Dict[str, object] = {}

    def config(self, key: str, value) -> "TpuSparkSessionBuilder":
        self._conf[key] = value
        return self

    def master(self, _: str) -> "TpuSparkSessionBuilder":
        return self

    def appName(self, _: str) -> "TpuSparkSessionBuilder":
        return self

    def getOrCreate(self) -> "TpuSparkSession":
        return TpuSparkSession(self._conf)


class DataFrameReader:
    def __init__(self, session: "TpuSparkSession"):
        self.session = session
        self._options: Dict[str, object] = {}
        self._schema = None
        self._format = "parquet"

    def option(self, k, v):
        self._options[k] = v
        return self

    def schema(self, s):
        if not hasattr(s, "fields"):
            # a pyarrow.Schema normalizes to the engine StructType here
            # so every format reader sees one schema shape
            from spark_rapids_tpu.columnar.arrow_bridge import (
                schema_from_arrow,
            )

            s = schema_from_arrow(s)
        self._schema = s
        return self

    def format(self, fmt: str):
        self._format = fmt
        return self

    def load(self, path: str):
        from spark_rapids_tpu.io.datasource import lookup_format

        ext = lookup_format(self._format)
        if ext is None:
            # built-in providers (iceberg, ...) register on first use
            import spark_rapids_tpu.lakehouse  # noqa: F401

            ext = lookup_format(self._format)
        if ext is not None:
            return ext(self.session, path, self._schema, self._options)
        if self._format == "delta":
            return self.delta(path)
        return getattr(self, self._format)(path)

    def delta(self, path: str):
        from spark_rapids_tpu.lakehouse.delta import read_delta

        return read_delta(self.session, path)

    def hivetext(self, *paths: str):
        """Hive LazySimpleSerDe text table ('\\x01' fields, '\\N'
        nulls); requires .schema(...) since the format has no header."""
        from spark_rapids_tpu.api.dataframe import DataFrame
        from spark_rapids_tpu.plan.logical import FileScan

        if self._schema is None:
            raise ValueError("hivetext requires an explicit schema")
        schema = self._schema
        if not hasattr(schema, "fields"):
            from spark_rapids_tpu.columnar.arrow_bridge import (
                schema_from_arrow,
            )

            schema = schema_from_arrow(schema)
        return DataFrame(FileScan("hivetext", list(paths), schema,
                                  self._options), self.session)

    def parquet(self, *paths: str):
        import pyarrow as _pa

        from spark_rapids_tpu.api.dataframe import DataFrame
        from spark_rapids_tpu.columnar.arrow_bridge import schema_from_arrow
        from spark_rapids_tpu.io.readers import (
            discover_partitions,
            expand_paths,
            infer_parquet_schema,
        )
        from spark_rapids_tpu.plan.logical import FileScan

        files = expand_paths(list(paths), ".parquet")
        from spark_rapids_tpu.io.readers import resolve_input_paths

        part_cols, file_values = discover_partitions(
            files, resolve_input_paths(list(paths)))
        opts = dict(self._options)
        arrow_schema = (None if self._schema is not None
                        else infer_parquet_schema(list(paths)))
        if part_cols:
            # partition columns materialize from the directory layout
            # (PartitioningAwareFileIndex role); they are appended
            # after the file columns, Spark-style. With an explicit
            # user schema the spec still attaches — the values come
            # from the directories, typed per the declared field.
            if self._schema is None:
                for name, is_int in part_cols:
                    if name not in arrow_schema.names:
                        arrow_schema = arrow_schema.append(_pa.field(
                            name, _pa.int64() if is_int else _pa.string()))
            opts["partition_spec"] = (part_cols, file_values)
        schema = self._schema or schema_from_arrow(arrow_schema)
        return DataFrame(FileScan("parquet", list(paths), schema,
                                  opts), self.session)

    def csv(self, path: str, header: bool = True, **kw):
        from spark_rapids_tpu.api.dataframe import DataFrame
        from spark_rapids_tpu.columnar.arrow_bridge import schema_from_arrow
        from spark_rapids_tpu.io.readers import expand_paths, read_csv
        from spark_rapids_tpu.plan.logical import FileScan

        if self._schema is not None:
            schema = self._schema
        else:
            # schema inference samples ONE file — committed write
            # output is a directory of part files
            sample_path = (expand_paths([path], ".csv") or [path])[0]
            sample = read_csv(sample_path, header=header, **kw)
            schema = schema_from_arrow(sample.schema)
        opts = dict(self._options)
        opts["header"] = header
        return DataFrame(FileScan("csv", [path], schema, opts),
                         self.session)

    def json(self, path: str):
        from spark_rapids_tpu.api.dataframe import DataFrame
        from spark_rapids_tpu.columnar.arrow_bridge import schema_from_arrow
        from spark_rapids_tpu.io.readers import expand_paths, read_json
        from spark_rapids_tpu.plan.logical import FileScan

        if self._schema is not None:
            schema = self._schema
        else:
            sample_path = (expand_paths([path], ".json") or [path])[0]
            sample = read_json(sample_path)
            schema = schema_from_arrow(sample.schema)
        return DataFrame(FileScan("json", [path], schema, self._options),
                         self.session)

    def orc(self, *paths: str):
        from spark_rapids_tpu.api.dataframe import DataFrame
        from spark_rapids_tpu.columnar.arrow_bridge import schema_from_arrow
        from spark_rapids_tpu.io.readers import infer_orc_schema
        from spark_rapids_tpu.plan.logical import FileScan

        schema = self._schema or schema_from_arrow(
            infer_orc_schema(list(paths)))
        return DataFrame(FileScan("orc", list(paths), schema,
                                  self._options), self.session)

    def avro(self, *paths: str):
        from spark_rapids_tpu.api.dataframe import DataFrame
        from spark_rapids_tpu.columnar.arrow_bridge import schema_from_arrow
        from spark_rapids_tpu.io.readers import infer_avro_schema
        from spark_rapids_tpu.plan.logical import FileScan

        schema = self._schema or schema_from_arrow(
            infer_avro_schema(list(paths)))
        return DataFrame(FileScan("avro", list(paths), schema,
                                  self._options), self.session)


_active: Optional["TpuSparkSession"] = None
_active_lock = threading.Lock()


class TpuSparkSession:
    builder = None  # class attribute set below

    def __init__(self, conf: Optional[Dict[str, object]] = None):
        from spark_rapids_tpu.exec.relation_cache import CacheManager

        from spark_rapids_tpu.runtime.metrics import MetricsRegistry

        self._settings = dict(conf or {})
        self.rapids_conf = rc.RapidsConf(self._settings)
        self.cache_manager = CacheManager()
        # engine-dispatch observability (which engine ran each query and
        # why faster engines fell back — see DataFrame.collect_arrow)
        self.query_metrics = MetricsRegistry()
        self.last_execution = None
        self._init_runtime()
        # the session OWNS the observability wiring (obs/): event bus,
        # span builder, event history, and the conf-gated event-log
        # writer; runtime modules emit into it process-wide
        from spark_rapids_tpu.obs import ObsManager

        self.obs = ObsManager(self.rapids_conf)
        # conf-gated live scrape endpoint (/metrics, /queries) — the
        # first piece of the service front-end (obs/http.py)
        self.obs.start_http(self, self.rapids_conf)
        global _active
        with _active_lock:
            _active = self

    def _init_runtime(self):
        """Plugin lifecycle (Plugin.scala:412-545): driver init fixes
        up/broadcasts the conf, executor init brings up the device
        runtime. Standalone, both run here."""
        from spark_rapids_tpu.plugin import (
            TpuDriverPlugin,
            executor_plugin,
        )

        coord = self.rapids_conf.get(rc.MULTIHOST_COORDINATOR)
        if coord:
            # join the cluster BEFORE any backend touch so
            # jax.devices() spans every process (multihost.initialize
            # is idempotent across sessions in one process)
            from spark_rapids_tpu.parallel import multihost

            nproc = self.rapids_conf.get(rc.MULTIHOST_NUM_PROCESSES)
            pid = self.rapids_conf.get(rc.MULTIHOST_PROCESS_ID)
            multihost.initialize(
                coord, nproc if nproc > 0 else None,
                pid if pid >= 0 else None)
        self._conf_map = TpuDriverPlugin().init(self.rapids_conf)
        self._executor_plugin = executor_plugin()
        self._executor_plugin.init(self.rapids_conf)

    # --- conf ---

    class _ConfView:
        def __init__(self, session):
            self._s = session

        def get(self, key: str, default=None):
            try:
                return self._s.rapids_conf[key]
            except KeyError:
                return self._s._settings.get(key, default)

        def set(self, key: str, value):
            self._s._settings[key] = value
            self._s.rapids_conf = rc.RapidsConf(self._s._settings)

    @property
    def conf(self):
        return TpuSparkSession._ConfView(self)

    # --- UDF registry (UDFRegistration / hiveUDFs.scala surface) ---

    @property
    def udf(self):
        from spark_rapids_tpu.udf.hive_udf import UDFRegistration

        if not hasattr(self, "_udf_reg"):
            self._udf_reg = UDFRegistration(self)
        return self._udf_reg

    # --- data sources ---

    @property
    def read(self) -> DataFrameReader:
        return DataFrameReader(self)

    def createDataFrame(self, data, schema=None):
        from spark_rapids_tpu.api.dataframe import DataFrame
        from spark_rapids_tpu.plan.logical import LocalRelation

        if isinstance(data, pa.Table):
            table = data
        elif hasattr(data, "dtypes") and hasattr(data, "columns"):
            table = pa.Table.from_pandas(data, preserve_index=False)
        elif isinstance(data, dict):
            table = pa.table(data)
        elif isinstance(data, list) and schema is not None:
            names = schema if isinstance(schema, list) else schema.names
            cols = list(zip(*data)) if data else [[] for _ in names]
            table = pa.table({n: list(c) for n, c in zip(names, cols)})
        else:
            raise TypeError("createDataFrame accepts arrow Table, pandas "
                            "DataFrame, dict of columns, or list of rows "
                            "with schema")
        return DataFrame(LocalRelation(table), self)

    def range(self, start: int, end: Optional[int] = None, step: int = 1,
              numPartitions: int = 1):
        from spark_rapids_tpu.api.dataframe import DataFrame
        from spark_rapids_tpu.plan.logical import Range

        if end is None:
            start, end = 0, start
        return DataFrame(Range(start, end, step, numPartitions), self)

    # --- write ---

    def write_parquet(self, df, path: str):
        from spark_rapids_tpu.io.readers import write_parquet

        write_parquet(df.collect_arrow(), path)

    def explainPotentialTpuPlan(self, df) -> str:
        """Execute-free placement report: tag the plan and return the
        would-be device placement with fallback reasons (the ExplainPlan
        public API, reference GpuOverrides.scala:4500
        explainPotentialGpuPlan)."""
        _phys, meta = df._physical()
        txt = meta.explain(only_not_on_device=False)
        return txt or "(all operators place on device)"

    # --- profiling (NvtxWithMetrics / nvtx_profiling.md analog) ---

    def startProfiler(self, log_dir: str):
        from spark_rapids_tpu.runtime import profiler

        profiler.start_trace(log_dir)

    def stopProfiler(self):
        from spark_rapids_tpu.runtime import profiler

        profiler.stop_trace()

    @property
    def compile_cache_stats(self):
        """Process compile ledger (runtime/compile_cache.py): programs
        compiled / structural cache hits / warmup hits / compile
        seconds. Per-query deltas live in last_execution['compile']."""
        from spark_rapids_tpu.runtime.compile_cache import stats

        return stats.snapshot()

    @property
    def robustness_metrics(self):
        """One snapshot of every failure-domain counter (PR 2/3): chaos
        injections per site, backoff retries per domain, shuffle
        fetch/checksum recoveries + orphaned/discarded blocks,
        stage-scheduler recoveries (retries, speculation, recomputed
        partitions, evicted workers), degradation-ladder demotions +
        circuit-breaker state, quarantined compile artifacts, and
        semaphore timeouts. A view over the unified registry
        (obs/registry.py); keys are a stable contract. bench.py folds
        this into its JSON so BENCH_* tracks robustness overhead."""
        from spark_rapids_tpu.obs import registry as obs_registry

        return obs_registry.robustness_snapshot()

    def prometheus_metrics(self) -> str:
        """Every engine counter in Prometheus text exposition
        (obs/prom.py) — expose behind a scrape endpoint for
        dashboards."""
        from spark_rapids_tpu.obs import prom

        return prom.render(self)

    # --- query governance (runtime/admission.py) ---

    def cancel(self, query_id: int,
               reason: str = "cancelled by user") -> bool:
        """Cancel a running or queued query by the id reported in
        last_execution['queryId'] / the admission tables. A queued
        query leaves the queue immediately; a running one unwinds at
        its next cooperative yield point, releasing its semaphore
        permits and spill-catalog buffers. True when the cancel newly
        latched."""
        from spark_rapids_tpu.runtime import admission

        return admission.get().cancel(query_id, reason)

    def cancel_all(self, reason: str = "cancelled by user") -> int:
        """Cancel every running and queued query; returns how many
        tokens newly latched."""
        from spark_rapids_tpu.runtime import admission

        return admission.get().cancel_all(reason)

    def admission_status(self) -> dict:
        """Running + queued query tables (ids, priorities, elapsed
        time, descriptions) and the conf'd capacity — the table a
        QueryRejectedError prints, live."""
        from spark_rapids_tpu.runtime import admission

        return admission.get().status()

    # --- serving (serve/server.py) ---

    def serve(self, conf: Optional[Dict[str, object]] = None
              ) -> "object":
        """Start a query-service daemon over THIS session's warm
        engine and return it (already listening; `.port` carries the
        bound port). The daemon borrows the session — `daemon.stop()`
        drains and closes sockets but leaves the session running.
        `conf` entries are applied to the session settings first (the
        usual place to pass a fixed `spark.rapids.tpu.serve.port` or
        tenant caps)."""
        from spark_rapids_tpu.serve.server import QueryServiceDaemon

        if conf:
            for k, v in conf.items():
                self._settings[k] = v
            self.rapids_conf = rc.RapidsConf(self._settings)
        return QueryServiceDaemon(session=self).start()

    def stop(self):
        global _active
        try:
            # finalize any in-flight event log + release the bus (a
            # newer session's bus survives: uninstall is identity-gated)
            self.obs.close()
        except Exception:
            pass
        try:
            self.cache_manager.clear()
        except Exception:
            pass
        try:
            # drain pending compile-cache index/artifact writes so a
            # follow-on process (or the warm-cache bench probe) sees
            # everything this session compiled
            from spark_rapids_tpu.runtime import compile_cache

            compile_cache.flush()
        except Exception:
            pass
        try:
            from spark_rapids_tpu.runtime.memory import _catalog

            if _catalog is not None:
                _catalog.check_leaks(
                    raise_on_leak=bool(self.rapids_conf.get(
                        rc.LEAK_DETECTION)))
        finally:
            # admission permits of tasks the session abandoned (e.g. a
            # partially-consumed ColumnarRdd iterator) must not starve
            # the next session — the executor-plugin shutdown resets
            # GpuSemaphore likewise
            from spark_rapids_tpu.runtime import semaphore as _sem

            _sem.initialize(
                self.rapids_conf.get(rc.CONCURRENT_TPU_TASKS),
                self.rapids_conf.get(rc.SEMAPHORE_ACQUIRE_TIMEOUT_MS))
            # the session must deregister even when the leak check
            # raises, or active() keeps returning a dead session
            with _active_lock:
                _active = None

    @staticmethod
    def active() -> Optional["TpuSparkSession"]:
        return _active


TpuSparkSession.builder = TpuSparkSessionBuilder()
