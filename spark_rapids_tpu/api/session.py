"""Placeholder session (built out with the planner)."""


class TpuSparkSession:
    pass
