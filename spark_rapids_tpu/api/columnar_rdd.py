"""Zero-copy ML handoff — the ColumnarRdd analog (reference
ColumnarRdd.scala:42-51, InternalColumnarRddConverter.scala: exports the
device-resident cuDF tables of a query to XGBoost-style consumers
without a host round trip).

Here the device currency is the ColumnBatch pytree of jax arrays, which
IS the native input format for JAX/flax ML code — so the handoff is the
identity: execute the plan and hand out the device batches (or a single
stacked dict of jnp arrays for a whole partition set)."""

from __future__ import annotations

from typing import Dict, Iterator, Tuple

import jax.numpy as jnp

from spark_rapids_tpu.columnar.batch import ColumnBatch, concat_batches


class ColumnarRdd:
    @staticmethod
    def convert(df) -> Iterator[ColumnBatch]:
        """Execute the plan, yielding DEVICE ColumnBatches per partition
        (no host conversion for device-resident operators)."""
        from spark_rapids_tpu.columnar.arrow_bridge import arrow_to_device
        from spark_rapids_tpu.exec.base import new_task_context
        from spark_rapids_tpu.runtime import semaphore as sem

        phys, _ = df._physical()
        for pid in range(phys.num_partitions):
            ctx = new_task_context(df.session.rapids_conf)
            try:
                for payload in phys.execute_partition(pid, ctx):
                    if isinstance(payload, ColumnBatch):
                        yield payload
                    else:
                        yield arrow_to_device(payload)
            finally:
                # the partition task's admission permits return when
                # the consumer moves on (or closes the generator) —
                # GpuSemaphore releases at task completion likewise
                sem.get().release_if_necessary(ctx.task_id)

    @staticmethod
    def to_jax(df) -> Dict[str, Tuple[jnp.ndarray, jnp.ndarray]]:
        """Whole-result handoff: {column -> (values[:n], validity[:n])}
        of device arrays, ready for jnp/flax consumption."""
        batches = list(ColumnarRdd.convert(df))
        if not batches:
            raise ValueError("empty result")
        merged = concat_batches(batches) if len(batches) > 1 else \
            batches[0]
        n = merged.row_count()
        out = {}
        for f, c in zip(merged.schema.fields, merged.columns):
            out[f.name] = (c.data[:n], c.validity[:n])
        return out
