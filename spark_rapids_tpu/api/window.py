"""pyspark.sql.window-compatible Window/WindowSpec builder."""

from __future__ import annotations

from typing import List, Optional

from spark_rapids_tpu.api.column import Column, SortColumn, _expr
from spark_rapids_tpu.expr.windows import WindowFrame, WindowSpecDef
from spark_rapids_tpu.plan.logical import SortOrder

_UNBOUNDED = (1 << 63) - 1


def _order_of(c) -> SortOrder:
    if isinstance(c, SortColumn):
        return SortOrder(c.expr, c.ascending, c.nulls_first)
    if isinstance(c, str):
        from spark_rapids_tpu.api.functions import UnresolvedColumn

        return SortOrder(UnresolvedColumn(c))
    return SortOrder(_expr(c))


def _part_of(c):
    if isinstance(c, str):
        from spark_rapids_tpu.api.functions import UnresolvedColumn

        return UnresolvedColumn(c)
    return _expr(c)


def _bound(v):
    """pyspark boundary value -> internal (None=unbounded, 0=current);
    float offsets (rangeBetween over double keys) pass through intact."""
    if v <= -_UNBOUNDED or v >= _UNBOUNDED:
        return None
    return int(v) if isinstance(v, int) else float(v)


class WindowSpec:
    def __init__(self, partitions=(), orders=(),
                 frame: Optional[WindowFrame] = None):
        self._partitions = list(partitions)
        self._orders = list(orders)
        self._frame = frame

    def partitionBy(self, *cols) -> "WindowSpec":
        return WindowSpec(self._partitions + [_part_of(c) for c in cols],
                          self._orders, self._frame)

    def orderBy(self, *cols) -> "WindowSpec":
        return WindowSpec(self._partitions,
                          self._orders + [_order_of(c) for c in cols],
                          self._frame)

    def rowsBetween(self, start: int, end: int) -> "WindowSpec":
        return WindowSpec(self._partitions, self._orders,
                          WindowFrame("rows", _bound(start), _bound(end)))

    def rangeBetween(self, start: int, end: int) -> "WindowSpec":
        return WindowSpec(self._partitions, self._orders,
                          WindowFrame("range", _bound(start), _bound(end)))

    def to_spec_def(self) -> WindowSpecDef:
        return WindowSpecDef(self._partitions, self._orders, self._frame)


class Window:
    unboundedPreceding = -_UNBOUNDED
    unboundedFollowing = _UNBOUNDED
    currentRow = 0

    @staticmethod
    def partitionBy(*cols) -> WindowSpec:
        return WindowSpec().partitionBy(*cols)

    @staticmethod
    def orderBy(*cols) -> WindowSpec:
        return WindowSpec().orderBy(*cols)

    @staticmethod
    def rowsBetween(start: int, end: int) -> WindowSpec:
        return WindowSpec().rowsBetween(start, end)

    @staticmethod
    def rangeBetween(start: int, end: int) -> WindowSpec:
        return WindowSpec().rangeBetween(start, end)
