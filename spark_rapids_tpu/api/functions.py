"""pyspark.sql.functions-compatible surface (the subset backing v1)."""

from __future__ import annotations

from typing import Any

from spark_rapids_tpu.api.column import Column, _expr
from spark_rapids_tpu.expr import (
    Abs, Alias, Average, CaseWhen, Cast, Coalesce, Concat, Count,
    DayOfMonth, First, Hour, Length, Literal, Lower, Max, Min, Minute,
    Murmur3Hash, Second, Substring, Sum, Upper, Year, Month,
)

# unresolved column marker: resolved by DataFrame against its schema


class UnresolvedColumn:
    def __init__(self, name: str):
        self.name = name


def col(name: str) -> Column:
    return Column(UnresolvedColumn(name), name)  # type: ignore[arg-type]


def lit(v: Any) -> Column:
    return Column(Literal(v))


def expr_of(c) -> Any:
    if isinstance(c, Column):
        return c.expr
    if isinstance(c, str):
        # bare strings name columns (pyspark convention for functions)
        return UnresolvedColumn(c)
    return _expr(c)


# --- aggregates ---

def sum(c) -> Column:  # noqa: A001
    return Column(Sum(expr_of(c)))


def count(c="*") -> Column:
    if isinstance(c, str) and c == "*":
        return Column(Count(None))
    return Column(Count(expr_of(c)))


def avg(c) -> Column:
    return Column(Average(expr_of(c)))


mean = avg


def min(c) -> Column:  # noqa: A001
    return Column(Min(expr_of(c)))


def max(c) -> Column:  # noqa: A001
    return Column(Max(expr_of(c)))


def first(c, ignorenulls: bool = False) -> Column:
    return Column(First(expr_of(c), ignore_nulls=ignorenulls))


def _agg1(cls, c):
    return Column(cls(expr_of(c)))


def stddev(c) -> Column:
    from spark_rapids_tpu.expr.aggregates import StddevSamp

    return _agg1(StddevSamp, c)


stddev_samp = stddev


def stddev_pop(c) -> Column:
    from spark_rapids_tpu.expr.aggregates import StddevPop

    return _agg1(StddevPop, c)


def variance(c) -> Column:
    from spark_rapids_tpu.expr.aggregates import VarianceSamp

    return _agg1(VarianceSamp, c)


var_samp = variance


def var_pop(c) -> Column:
    from spark_rapids_tpu.expr.aggregates import VariancePop

    return _agg1(VariancePop, c)


def skewness(c) -> Column:
    from spark_rapids_tpu.expr.aggregates import Skewness

    return _agg1(Skewness, c)


def kurtosis(c) -> Column:
    from spark_rapids_tpu.expr.aggregates import Kurtosis

    return _agg1(Kurtosis, c)


def corr(x, y) -> Column:
    from spark_rapids_tpu.expr.aggregates import Corr

    return Column(Corr(expr_of(x), expr_of(y)))


def covar_pop(x, y) -> Column:
    from spark_rapids_tpu.expr.aggregates import CovarPop

    return Column(CovarPop(expr_of(x), expr_of(y)))


def covar_samp(x, y) -> Column:
    from spark_rapids_tpu.expr.aggregates import CovarSamp

    return Column(CovarSamp(expr_of(x), expr_of(y)))


def collect_list(c) -> Column:
    from spark_rapids_tpu.expr.aggregates import CollectList

    return _agg1(CollectList, c)


array_agg = collect_list


def collect_set(c) -> Column:
    from spark_rapids_tpu.expr.aggregates import CollectSet

    return _agg1(CollectSet, c)


def countDistinct(c) -> Column:
    from spark_rapids_tpu.expr.aggregates import CountDistinct

    return _agg1(CountDistinct, c)


count_distinct = countDistinct


def sumDistinct(c) -> Column:
    from spark_rapids_tpu.expr.aggregates import SumDistinct

    return _agg1(SumDistinct, c)


sum_distinct = sumDistinct


def percentile(c, percentage: float) -> Column:
    from spark_rapids_tpu.expr.aggregates import Percentile

    return Column(Percentile(expr_of(c), percentage))


def percentile_approx(c, percentage: float,
                      accuracy: int = 10000) -> Column:
    from spark_rapids_tpu.expr.aggregates import ApproxPercentile

    return Column(ApproxPercentile(expr_of(c), percentage, accuracy))


approx_percentile = percentile_approx


def bool_and(c) -> Column:
    from spark_rapids_tpu.expr.aggregates import BoolAnd

    return _agg1(BoolAnd, c)


every = bool_and


def bool_or(c) -> Column:
    from spark_rapids_tpu.expr.aggregates import BoolOr

    return _agg1(BoolOr, c)


some = bool_or


def any_value(c, ignorenulls: bool = True) -> Column:
    from spark_rapids_tpu.expr.aggregates import AnyValue

    return Column(AnyValue(expr_of(c), ignore_nulls=ignorenulls))


def grouping_id() -> Column:
    """Grouping-set id inside rollup/cube/groupingSets agg()."""
    from spark_rapids_tpu.expr.aggregates import GroupingID

    return Column(GroupingID(), "spark_grouping_id()")


def grouping(c) -> Column:
    """1 when the column is aggregated in the current grouping set."""
    from spark_rapids_tpu.expr.aggregates import GroupingBit

    return Column(GroupingBit(expr_of(c)))


# --- scalar functions ---

def abs(c) -> Column:  # noqa: A001
    return Column(Abs(expr_of(c)))


def coalesce(*cs) -> Column:
    return Column(Coalesce(*[expr_of(c) for c in cs]))


def concat(*cs) -> Column:
    return Column(Concat(*[expr_of(c) for c in cs]))


def substring(c, pos: int, length: int) -> Column:
    return Column(Substring(expr_of(c), pos, length))


def upper(c) -> Column:
    return Column(Upper(expr_of(c)))


def lower(c) -> Column:
    return Column(Lower(expr_of(c)))


def length(c) -> Column:
    return Column(Length(expr_of(c)))


def _dt(cls, *args):
    from spark_rapids_tpu.expr import datetimes as DT

    return Column(getattr(DT, cls)(*args))


def year(c) -> Column:
    return Column(Year(expr_of(c)))


def dayofweek(c) -> Column:
    return _dt("DayOfWeek", expr_of(c))


def weekday(c) -> Column:
    return _dt("WeekDay", expr_of(c))


def dayofyear(c) -> Column:
    return _dt("DayOfYear", expr_of(c))


def weekofyear(c) -> Column:
    return _dt("WeekOfYear", expr_of(c))


def quarter(c) -> Column:
    return _dt("Quarter", expr_of(c))


def last_day(c) -> Column:
    return _dt("LastDay", expr_of(c))


def date_add(c, days) -> Column:
    return _dt("DateAdd", expr_of(c), expr_of(lit_or(days)))


def date_sub(c, days) -> Column:
    return _dt("DateSub", expr_of(c), expr_of(lit_or(days)))


def datediff(end, start) -> Column:
    return _dt("DateDiff", expr_of(end), expr_of(start))


def add_months(c, months) -> Column:
    return _dt("AddMonths", expr_of(c), expr_of(lit_or(months)))


def months_between(end, start, roundOff: bool = True) -> Column:
    from spark_rapids_tpu.expr.datetimes import MonthsBetween

    return Column(MonthsBetween(expr_of(end), expr_of(start), roundOff))


def next_day(c, dayOfWeek: str) -> Column:
    return _dt("NextDay", expr_of(c), dayOfWeek)


def trunc(c, fmt: str) -> Column:
    return _dt("TruncDate", expr_of(c), fmt)


def date_trunc(fmt: str, c) -> Column:
    return _dt("DateTrunc", fmt, expr_of(c))


def unix_timestamp(c) -> Column:
    from spark_rapids_tpu.expr.datetimes import UnixTimestamp

    # string/date input routes through the cast machinery first
    # (no-op for timestamps)
    return Column(UnixTimestamp(_StringToTs(expr_of(c))))


def _StringToTs(e):
    from spark_rapids_tpu.expr import Cast
    from spark_rapids_tpu.sqltypes.datatypes import timestamp as _ts_t

    return Cast(e, _ts_t)


def from_unixtime(c, fmt: str = "yyyy-MM-dd HH:mm:ss") -> Column:
    return _dt("FromUnixtime", expr_of(c), fmt)


def timestamp_seconds(c) -> Column:
    return _dt("SecondsToTimestamp", expr_of(c))


def make_date(y, m, d) -> Column:
    return _dt("MakeDate", expr_of(y), expr_of(m), expr_of(d))


def from_utc_timestamp(c, tz: str) -> Column:
    return _dt("FromUtcTimestamp", expr_of(c), tz)


def to_utc_timestamp(c, tz: str) -> Column:
    return _dt("ToUtcTimestamp", expr_of(c), tz)


def date_format(c, fmt: str) -> Column:
    return _dt("DateFormat", expr_of(c), fmt)


def to_date(c, fmt: str = None) -> Column:
    from spark_rapids_tpu.expr import Cast
    from spark_rapids_tpu.sqltypes.datatypes import date as _date_t

    if fmt is not None and fmt not in ("yyyy-MM-dd",):
        raise ValueError(
            f"to_date format {fmt!r} unsupported in v1 (default "
            "'yyyy-MM-dd' only)")
    return Column(Cast(expr_of(c), _date_t))


def to_timestamp(c, fmt: str = None) -> Column:
    from spark_rapids_tpu.expr import Cast
    from spark_rapids_tpu.sqltypes.datatypes import timestamp as _ts_t

    if fmt is not None and fmt not in ("yyyy-MM-dd HH:mm:ss",
                                       "yyyy-MM-dd"):
        raise ValueError(
            f"to_timestamp format {fmt!r} unsupported in v1")
    return Column(Cast(expr_of(c), _ts_t))


def current_date() -> Column:
    return _dt("CurrentDate")


def current_timestamp() -> Column:
    return _dt("CurrentTimestamp")


def month(c) -> Column:
    return Column(Month(expr_of(c)))


def dayofmonth(c) -> Column:
    return Column(DayOfMonth(expr_of(c)))


def hour(c) -> Column:
    return Column(Hour(expr_of(c)))


def minute(c) -> Column:
    return Column(Minute(expr_of(c)))


def second(c) -> Column:
    return Column(Second(expr_of(c)))


def hash(*cs) -> Column:  # noqa: A001
    return Column(Murmur3Hash(*[expr_of(c) for c in cs]))


# --- window functions ---

def row_number() -> Column:
    from spark_rapids_tpu.expr.windows import RowNumber

    return Column(RowNumber())


def rank() -> Column:
    from spark_rapids_tpu.expr.windows import Rank

    return Column(Rank())


def dense_rank() -> Column:
    from spark_rapids_tpu.expr.windows import DenseRank

    return Column(DenseRank())


def percent_rank() -> Column:
    from spark_rapids_tpu.expr.windows import PercentRank

    return Column(PercentRank())


def cume_dist() -> Column:
    from spark_rapids_tpu.expr.windows import CumeDist

    return Column(CumeDist())


def ntile(n: int) -> Column:
    from spark_rapids_tpu.expr.windows import NTile

    return Column(NTile(n))


def lead(c, offset: int = 1, default=None) -> Column:
    from spark_rapids_tpu.expr.windows import Lead

    d = None if default is None else _expr(lit_or(default))
    return Column(Lead(expr_of(c), offset, d))


def lag(c, offset: int = 1, default=None) -> Column:
    from spark_rapids_tpu.expr.windows import Lag

    d = None if default is None else _expr(lit_or(default))
    return Column(Lag(expr_of(c), offset, d))


def when(condition: Column, value) -> "WhenBuilder":
    return WhenBuilder([(expr_of(condition), expr_of(lit_or(value)))])


def lit_or(v):
    return v if isinstance(v, Column) else lit(v)


class WhenBuilder(Column):
    def __init__(self, branches):
        self._branches = branches
        super().__init__(CaseWhen(branches))

    def when(self, condition: Column, value) -> "WhenBuilder":
        return WhenBuilder(self._branches +
                           [(expr_of(condition), expr_of(lit_or(value)))])

    def otherwise(self, value) -> Column:
        return Column(CaseWhen(self._branches, expr_of(lit_or(value))))


# --- math / bitwise (reference arithmetic.scala + mathExpressions rules) ---

def _u(cls):
    def f(c):
        return Column(cls(expr_of(c)))
    f.__name__ = cls.__name__.lower()
    return f


from spark_rapids_tpu.expr import (  # noqa: E402
    Acos, Acosh, Ascii, Asin, Asinh, Atan, Atan2, Atanh, BitwiseNot, BRound,
    Cbrt, Ceil, Chr, ConcatWs, Cos, Cosh, Cot, Exp, Expm1, Floor, Greatest,
    Hex, Hypot, InitCap, Least, Log, Log10, Log1p, Log2, Logarithm, NaNvl,
    Nvl2, Pow, Rint, Round, ShiftLeft, ShiftRight, ShiftRightUnsigned,
    Signum, Sin, Sinh, Sqrt, StringInstr, StringLocate, StringLPad,
    StringRepeat, StringReplace, StringReverse, StringRPad, StringTranslate,
    StringTrim, StringTrimLeft, StringTrimRight, SubstringIndex, Tan, Tanh,
    ToDegrees, ToRadians, XxHash64,
)

sqrt = _u(Sqrt)
exp = _u(Exp)
expm1 = _u(Expm1)
cbrt = _u(Cbrt)
rint = _u(Rint)
signum = _u(Signum)
sin = _u(Sin)
cos = _u(Cos)
tan = _u(Tan)
cot = _u(Cot)
asin = _u(Asin)
acos = _u(Acos)
atan = _u(Atan)
sinh = _u(Sinh)
cosh = _u(Cosh)
tanh = _u(Tanh)
asinh = _u(Asinh)
acosh = _u(Acosh)
atanh = _u(Atanh)
degrees = _u(ToDegrees)
radians = _u(ToRadians)
log10 = _u(Log10)
log2 = _u(Log2)
log1p = _u(Log1p)
bitwise_not = _u(BitwiseNot)
bitwiseNOT = bitwise_not
hex = _u(Hex)  # noqa: A001
ascii = _u(Ascii)  # noqa: A001
initcap = _u(InitCap)
reverse = _u(StringReverse)
ltrim = _u(StringTrimLeft)
rtrim = _u(StringTrimRight)
trim = _u(StringTrim)


def log(arg1, arg2=None) -> Column:
    """log(col) = natural log; log(base, col) = log base."""
    if arg2 is None:
        return Column(Log(expr_of(arg1)))
    return Column(Logarithm(expr_of(lit_or(arg1)), expr_of(arg2)))


def pow(base, exponent) -> Column:  # noqa: A001
    return Column(Pow(expr_of(lit_or(base)), expr_of(lit_or(exponent))))


def atan2(y, x) -> Column:
    return Column(Atan2(expr_of(lit_or(y)), expr_of(lit_or(x))))


def hypot(a, b) -> Column:
    return Column(Hypot(expr_of(lit_or(a)), expr_of(lit_or(b))))


def round(c, scale: int = 0) -> Column:  # noqa: A001
    return Column(Round(expr_of(c), scale))


def bround(c, scale: int = 0) -> Column:
    return Column(BRound(expr_of(c), scale))


def ceil(c) -> Column:
    return Column(Ceil(expr_of(c)))


def floor(c) -> Column:
    return Column(Floor(expr_of(c)))


def shiftleft(c, n: int) -> Column:
    return Column(ShiftLeft(expr_of(c), expr_of(lit(n))))


def shiftright(c, n: int) -> Column:
    return Column(ShiftRight(expr_of(c), expr_of(lit(n))))


def shiftrightunsigned(c, n: int) -> Column:
    return Column(ShiftRightUnsigned(expr_of(c), expr_of(lit(n))))


def greatest(*cs) -> Column:
    return Column(Greatest(*[expr_of(c) for c in cs]))


def least(*cs) -> Column:
    return Column(Least(*[expr_of(c) for c in cs]))


def nvl(a, b) -> Column:
    return Column(Coalesce(expr_of(a), expr_of(lit_or(b))))


def nvl2(a, b, c) -> Column:
    return Column(Nvl2(expr_of(a), expr_of(lit_or(b)), expr_of(lit_or(c))))


def nanvl(a, b) -> Column:
    return Column(NaNvl(expr_of(a), expr_of(lit_or(b))))


def xxhash64(*cs) -> Column:
    return Column(XxHash64(*[expr_of(c) for c in cs]))


# --- string breadth ---

def lpad(c, length: int, pad: str = " ") -> Column:
    return Column(StringLPad(expr_of(c), length, pad))


def rpad(c, length: int, pad: str = " ") -> Column:
    return Column(StringRPad(expr_of(c), length, pad))


def repeat(c, n: int) -> Column:
    return Column(StringRepeat(expr_of(c), n))


def instr(c, substr: str) -> Column:
    return Column(StringInstr(expr_of(c), substr))


def locate(substr: str, c, pos: int = 1) -> Column:
    return Column(StringLocate(expr_of(c), substr, pos))


def translate(c, matching: str, replace: str) -> Column:
    return Column(StringTranslate(expr_of(c), matching, replace))


def regexp_replace_literal(c, search: str, replacement: str) -> Column:
    """Literal (non-regex) replace — Spark's `replace`."""
    return Column(StringReplace(expr_of(c), search, replacement))


replace = regexp_replace_literal


def concat_ws(sep: str, *cs) -> Column:
    return Column(ConcatWs(sep, *[expr_of(c) for c in cs]))


def chr_(c) -> Column:
    return Column(Chr(expr_of(lit_or(c))))


def substring_index(c, delim: str, count: int) -> Column:
    return Column(SubstringIndex(expr_of(c), delim, count))


def rlike(c, pattern: str) -> Column:
    from spark_rapids_tpu.expr.regexexpr import RLike

    return Column(RLike(expr_of(c), pattern))


def regexp_extract(c, pattern: str, idx: int = 1) -> Column:
    from spark_rapids_tpu.expr.regexexpr import RegexpExtract

    return Column(RegexpExtract(expr_of(c), pattern, idx))


def regexp_replace(c, pattern: str, replacement: str) -> Column:
    from spark_rapids_tpu.expr.regexexpr import RegexpReplace

    return Column(RegexpReplace(expr_of(c), pattern, replacement))


def udf(f=None, returnType=None):
    """Compile a Python function to device expressions (the udf-compiler
    analog); uncompilable functions fall back to rowwise host execution.

        my_fn = F.udf(lambda x: x * 2 + 1, returnType=long)
        df.select(my_fn(df["v"]).alias("out"))
    """
    from spark_rapids_tpu.sqltypes.datatypes import double as _dbl

    rtype = returnType if returnType is not None else _dbl

    def wrap(fn):
        def apply(*cols) -> Column:
            from spark_rapids_tpu.udf.pyudf import PythonUDF

            exprs = [expr_of(c) for c in cols]
            # compilation is deferred to column resolution, when the
            # argument expressions carry concrete types
            marker = PythonUDF(fn, exprs, rtype)
            marker._wants_compile = True
            return Column(marker, getattr(fn, "__name__", "udf"))

        apply.fn = fn
        apply.returnType = rtype
        return apply

    if f is not None:
        return wrap(f)
    return wrap


def call_udf(name: str, *cols) -> Column:
    """Invoke a UDF registered on the active session
    (spark.udf.register / registerHive / registerDevice)."""
    from spark_rapids_tpu.api.session import TpuSparkSession
    from spark_rapids_tpu.udf.hive_udf import call_registered

    session = TpuSparkSession.active()
    if session is None:
        raise RuntimeError("no active session for call_udf")
    return call_registered(session, name, cols)


def pandas_udf(f=None, returnType=None):
    """Scalar pandas UDF: runs over pandas Series in a worker-process
    pool via Arrow IPC (the GpuArrowEvalPythonExec exchange analog,
    udf/pandas_udf.py).

        @F.pandas_udf(returnType=double)
        def plus_one(s):
            return s + 1.0
        df.select(plus_one(df["v"]).alias("out"))
    """
    from spark_rapids_tpu.sqltypes.datatypes import double as _dbl

    rtype = returnType if returnType is not None else _dbl
    if isinstance(rtype, str):
        from spark_rapids_tpu.sqltypes.datatypes import parse_type_name

        rtype = parse_type_name(rtype)

    def wrap(fn):
        def apply(*cols) -> Column:
            from spark_rapids_tpu.udf.pandas_udf import PandasUDF

            exprs = [expr_of(c) for c in cols]
            return Column(PandasUDF(fn, rtype, exprs),
                          getattr(fn, "__name__", "pandas_udf"))

        apply.fn = fn
        apply.returnType = rtype
        return apply

    if f is not None:
        return wrap(f)
    return wrap


# --- collection functions (collectionOperations.scala analog) ---

def size(c) -> Column:
    from spark_rapids_tpu.expr.collections import Size

    return Column(Size(expr_of(c)), "size")


def array_contains(c, value) -> Column:
    from spark_rapids_tpu.expr.collections import ArrayContains

    return Column(ArrayContains(expr_of(c), expr_of(value)),
                  "array_contains")


def element_at(c, index) -> Column:
    from spark_rapids_tpu.expr.collections import ElementAt

    return Column(ElementAt(expr_of(c), expr_of(index)), "element_at")


def array(*cols) -> Column:
    from spark_rapids_tpu.expr.collections import CreateArray

    return Column(CreateArray(*[expr_of(c) for c in cols]), "array")


def get_item(c, index) -> Column:
    from spark_rapids_tpu.expr.collections import GetArrayItem

    return Column(GetArrayItem(expr_of(c), expr_of(index)), "getItem")


def struct(*cols) -> Column:
    """struct(col1, col2, ...) — named after each column's output name
    (Spark CreateNamedStruct)."""
    from spark_rapids_tpu.expr.structs import CreateNamedStruct

    names = []
    exprs = []
    for i, c in enumerate(cols):
        if isinstance(c, Column):
            names.append(c._name or f"col{i + 1}")
        elif isinstance(c, str):
            names.append(c)
        else:
            names.append(f"col{i + 1}")
        exprs.append(expr_of(c))
    return Column(CreateNamedStruct(names, exprs), "struct")


def explode(c) -> Column:
    from spark_rapids_tpu.expr.generators import Explode

    return Column(Explode(expr_of(c)), "col")


def posexplode(c) -> Column:
    from spark_rapids_tpu.expr.generators import PosExplode

    return Column(PosExplode(expr_of(c)), "col")


def device_udf(f=None, returnType=None):
    """Columnar device UDF (the RapidsUDF analog, expr/deviceudf.py):
    the function receives jnp value/validity arrays and is traced into
    the enclosing XLA program.

        @F.device_udf(returnType=double)
        def scaled(v, v_valid):
            return v * 2.0 + 1.0, v_valid
    """
    from spark_rapids_tpu.sqltypes.datatypes import double as _dbl

    rtype = returnType if returnType is not None else _dbl
    if isinstance(rtype, str):
        from spark_rapids_tpu.sqltypes.datatypes import parse_type_name

        rtype = parse_type_name(rtype)

    def wrap(fn):
        def apply(*cols) -> Column:
            from spark_rapids_tpu.expr.deviceudf import DeviceUDF

            return Column(DeviceUDF(fn, rtype,
                                    [expr_of(c) for c in cols]),
                          getattr(fn, "__name__", "device_udf"))

        apply.fn = fn
        return apply

    if f is not None:
        return wrap(f)
    return wrap


def transform(c, fn) -> Column:
    """transform(arr, x -> f(x)): fn takes and returns a Column; the
    lambda runs ON DEVICE, fused into the projection
    (higherOrderFunctions.scala analog). The lambda tree is built once
    the array column resolves to a concrete type."""
    from spark_rapids_tpu.expr.collections import ArrayTransform

    return Column(ArrayTransform(expr_of(c), fn=fn), "transform")


def filter_array(c, fn) -> Column:
    """filter(arr, x -> pred(x)) on device."""
    from spark_rapids_tpu.expr.collections import ArrayFilter

    return Column(ArrayFilter(expr_of(c), fn=fn), "filter")


def array_max(c) -> Column:
    from spark_rapids_tpu.expr.collections import ArrayMax

    return Column(ArrayMax(expr_of(c)), "array_max")


def array_min(c) -> Column:
    from spark_rapids_tpu.expr.collections import ArrayMin

    return Column(ArrayMin(expr_of(c)), "array_min")


def _coll(cls_name, *args):
    from spark_rapids_tpu.expr import collections as CX

    return Column(getattr(CX, cls_name)(*args))


def slice(c, start, length) -> Column:  # noqa: A001
    return _coll("Slice", expr_of(c), expr_of(lit_or(start)),
                 expr_of(lit_or(length)))


def array_position(c, v) -> Column:
    return _coll("ArrayPosition", expr_of(c), expr_of(lit_or(v)))


def array_remove(c, v) -> Column:
    return _coll("ArrayRemove", expr_of(c), expr_of(lit_or(v)))


def array_distinct(c) -> Column:
    return _coll("ArrayDistinct", expr_of(c))


def reverse(c) -> Column:
    return _coll("Reverse", expr_of(c))


def exists(c, fn) -> Column:
    from spark_rapids_tpu.expr.collections import ArrayExists

    return Column(ArrayExists(expr_of(c), fn=fn))


def forall(c, fn) -> Column:
    from spark_rapids_tpu.expr.collections import ArrayForall

    return Column(ArrayForall(expr_of(c), fn=fn))


def array_union(a, b) -> Column:
    return _coll("ArrayUnion", expr_of(a), expr_of(b))


def array_intersect(a, b) -> Column:
    return _coll("ArrayIntersect", expr_of(a), expr_of(b))


def array_except(a, b) -> Column:
    return _coll("ArrayExcept", expr_of(a), expr_of(b))


def arrays_overlap(a, b) -> Column:
    return _coll("ArraysOverlap", expr_of(a), expr_of(b))


def concat_arrays(*cs) -> Column:
    return _coll("ConcatArrays", *[expr_of(c) for c in cs])


def approx_count_distinct(c, rsd: float = 0.05) -> Column:
    """Exact distinct count (satisfies the approximation contract;
    reference: HLL++ sketches. `rsd` accepted for API parity)."""
    from spark_rapids_tpu.expr.aggregates import CountDistinct

    return Column(CountDistinct(expr_of(c)))


def map_keys(c) -> Column:
    from spark_rapids_tpu.expr.collections import MapKeys

    return Column(MapKeys(expr_of(c)))


def map_values(c) -> Column:
    from spark_rapids_tpu.expr.collections import MapValues

    return Column(MapValues(expr_of(c)))


def map_contains_key(c, key) -> Column:
    from spark_rapids_tpu.expr.collections import MapContainsKey

    return Column(MapContainsKey(expr_of(c), expr_of(lit_or(key))))


def create_map(*cols) -> Column:
    from spark_rapids_tpu.expr.collections import CreateMap

    return Column(CreateMap(*[expr_of(lit_or(c)) for c in cols]))


def map_from_arrays(keys, values) -> Column:
    from spark_rapids_tpu.expr.collections import MapFromArrays

    return Column(MapFromArrays(expr_of(keys), expr_of(values)))


def sort_array(c, asc: bool = True) -> Column:
    from spark_rapids_tpu.expr.collections import SortArray

    return Column(SortArray(expr_of(c), asc), "sort_array")


def get_json_object(c, path) -> Column:
    """get_json_object(json_str, '$.a.b[0]') — host-evaluated in v1
    (GpuGetJsonObject + JSONUtils JNI in the reference; the planner
    tags the operator for CPU fallback)."""
    from spark_rapids_tpu.expr.jsonexpr import GetJsonObject

    return Column(GetJsonObject(expr_of(c), path), "get_json_object")


def parse_url(c, part: str, key=None) -> Column:
    """parse_url(url, 'HOST'|'PATH'|'QUERY'[, query_key]) — host path
    in v1 (GpuParseUrl role)."""
    from spark_rapids_tpu.expr.jsonexpr import ParseUrl

    return Column(ParseUrl(expr_of(c), part, key), "parse_url")


def last(c, ignorenulls: bool = False) -> Column:
    from spark_rapids_tpu.expr.aggregates import Last

    return Column(Last(expr_of(c), ignore_nulls=ignorenulls), "last")
