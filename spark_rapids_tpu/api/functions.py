"""pyspark.sql.functions-compatible surface (the subset backing v1)."""

from __future__ import annotations

from typing import Any

from spark_rapids_tpu.api.column import Column, _expr
from spark_rapids_tpu.expr import (
    Abs, Alias, Average, CaseWhen, Cast, Coalesce, Concat, Count,
    DayOfMonth, First, Hour, Length, Literal, Lower, Max, Min, Minute,
    Murmur3Hash, Second, Substring, Sum, Upper, Year, Month,
)

# unresolved column marker: resolved by DataFrame against its schema


class UnresolvedColumn:
    def __init__(self, name: str):
        self.name = name


def col(name: str) -> Column:
    return Column(UnresolvedColumn(name), name)  # type: ignore[arg-type]


def lit(v: Any) -> Column:
    return Column(Literal(v))


def expr_of(c) -> Any:
    if isinstance(c, Column):
        return c.expr
    if isinstance(c, str):
        # bare strings name columns (pyspark convention for functions)
        return UnresolvedColumn(c)
    return _expr(c)


# --- aggregates ---

def sum(c) -> Column:  # noqa: A001
    return Column(Sum(expr_of(c)))


def count(c="*") -> Column:
    if isinstance(c, str) and c == "*":
        return Column(Count(None))
    return Column(Count(expr_of(c)))


def avg(c) -> Column:
    return Column(Average(expr_of(c)))


mean = avg


def min(c) -> Column:  # noqa: A001
    return Column(Min(expr_of(c)))


def max(c) -> Column:  # noqa: A001
    return Column(Max(expr_of(c)))


def first(c, ignorenulls: bool = False) -> Column:
    return Column(First(expr_of(c), ignore_nulls=ignorenulls))


# --- scalar functions ---

def abs(c) -> Column:  # noqa: A001
    return Column(Abs(expr_of(c)))


def coalesce(*cs) -> Column:
    return Column(Coalesce(*[expr_of(c) for c in cs]))


def concat(*cs) -> Column:
    return Column(Concat(*[expr_of(c) for c in cs]))


def substring(c, pos: int, length: int) -> Column:
    return Column(Substring(expr_of(c), pos, length))


def upper(c) -> Column:
    return Column(Upper(expr_of(c)))


def lower(c) -> Column:
    return Column(Lower(expr_of(c)))


def length(c) -> Column:
    return Column(Length(expr_of(c)))


def year(c) -> Column:
    return Column(Year(expr_of(c)))


def month(c) -> Column:
    return Column(Month(expr_of(c)))


def dayofmonth(c) -> Column:
    return Column(DayOfMonth(expr_of(c)))


def hour(c) -> Column:
    return Column(Hour(expr_of(c)))


def minute(c) -> Column:
    return Column(Minute(expr_of(c)))


def second(c) -> Column:
    return Column(Second(expr_of(c)))


def hash(*cs) -> Column:  # noqa: A001
    return Column(Murmur3Hash(*[expr_of(c) for c in cs]))


# --- window functions ---

def row_number() -> Column:
    from spark_rapids_tpu.expr.windows import RowNumber

    return Column(RowNumber())


def rank() -> Column:
    from spark_rapids_tpu.expr.windows import Rank

    return Column(Rank())


def dense_rank() -> Column:
    from spark_rapids_tpu.expr.windows import DenseRank

    return Column(DenseRank())


def percent_rank() -> Column:
    from spark_rapids_tpu.expr.windows import PercentRank

    return Column(PercentRank())


def cume_dist() -> Column:
    from spark_rapids_tpu.expr.windows import CumeDist

    return Column(CumeDist())


def ntile(n: int) -> Column:
    from spark_rapids_tpu.expr.windows import NTile

    return Column(NTile(n))


def lead(c, offset: int = 1, default=None) -> Column:
    from spark_rapids_tpu.expr.windows import Lead

    d = None if default is None else _expr(lit_or(default))
    return Column(Lead(expr_of(c), offset, d))


def lag(c, offset: int = 1, default=None) -> Column:
    from spark_rapids_tpu.expr.windows import Lag

    d = None if default is None else _expr(lit_or(default))
    return Column(Lag(expr_of(c), offset, d))


def when(condition: Column, value) -> "WhenBuilder":
    return WhenBuilder([(expr_of(condition), expr_of(lit_or(value)))])


def lit_or(v):
    return v if isinstance(v, Column) else lit(v)


class WhenBuilder(Column):
    def __init__(self, branches):
        self._branches = branches
        super().__init__(CaseWhen(branches))

    def when(self, condition: Column, value) -> "WhenBuilder":
        return WhenBuilder(self._branches +
                           [(expr_of(condition), expr_of(lit_or(value)))])

    def otherwise(self, value) -> Column:
        return Column(CaseWhen(self._branches, expr_of(lit_or(value))))
