"""Column — user-facing expression wrapper with Spark's operator surface."""

from __future__ import annotations

from typing import Any

from spark_rapids_tpu.expr import (
    Abs, Add, Alias, And, Cast, Contains, Divide, EndsWith, EqualNullSafe,
    EqualTo, GreaterThan, GreaterThanOrEqual, In, IsNaN, IsNotNull, IsNull,
    LessThan, LessThanOrEqual, Literal, Multiply, Not, Or, Pmod, Remainder,
    StartsWith, Subtract, UnaryMinus,
)
from spark_rapids_tpu.expr.core import Expression
from spark_rapids_tpu.sqltypes import DataType


def _expr(v: Any) -> Expression:
    if isinstance(v, Column):
        return v.expr
    if isinstance(v, Expression):
        return v
    return Literal(v)


class Column:
    def __init__(self, expr: Expression, name: str = None):
        self.expr = expr
        self._name = name

    @property
    def name(self) -> str:
        if self._name:
            return self._name
        if isinstance(self.expr, Alias):
            return self.expr.name
        return repr(self.expr)

    def alias(self, name: str) -> "Column":
        base = self.expr.children[0] if isinstance(self.expr, Alias) \
            else self.expr
        return Column(Alias(base, name), name)

    def getItem(self, index) -> "Column":
        from spark_rapids_tpu.expr.collections import GetArrayItem

        return Column(GetArrayItem(self.expr, _expr(index)), "getItem")

    def cast(self, to) -> "Column":
        if isinstance(to, str):
            from spark_rapids_tpu.sqltypes.datatypes import parse_type_name

            to = parse_type_name(to)
        return Column(Cast(self.expr, to))

    # arithmetic
    def __add__(self, o):
        return Column(Add(self.expr, _expr(o)))

    def __radd__(self, o):
        return Column(Add(_expr(o), self.expr))

    def __sub__(self, o):
        return Column(Subtract(self.expr, _expr(o)))

    def __rsub__(self, o):
        return Column(Subtract(_expr(o), self.expr))

    def __mul__(self, o):
        return Column(Multiply(self.expr, _expr(o)))

    def __rmul__(self, o):
        return Column(Multiply(_expr(o), self.expr))

    def __truediv__(self, o):
        return Column(Divide(self.expr, _expr(o)))

    def __rtruediv__(self, o):
        return Column(Divide(_expr(o), self.expr))

    def __mod__(self, o):
        return Column(Remainder(self.expr, _expr(o)))

    def __neg__(self):
        return Column(UnaryMinus(self.expr))

    # comparisons
    def __eq__(self, o):  # noqa: E711
        return Column(EqualTo(self.expr, _expr(o)))

    def __ne__(self, o):  # noqa: E711
        return Column(Not(EqualTo(self.expr, _expr(o))))

    def __lt__(self, o):
        return Column(LessThan(self.expr, _expr(o)))

    def __le__(self, o):
        return Column(LessThanOrEqual(self.expr, _expr(o)))

    def __gt__(self, o):
        return Column(GreaterThan(self.expr, _expr(o)))

    def __ge__(self, o):
        return Column(GreaterThanOrEqual(self.expr, _expr(o)))

    def eqNullSafe(self, o):
        return Column(EqualNullSafe(self.expr, _expr(o)))

    # boolean
    def __and__(self, o):
        return Column(And(self.expr, _expr(o)))

    def __or__(self, o):
        return Column(Or(self.expr, _expr(o)))

    def __invert__(self):
        return Column(Not(self.expr))

    # predicates
    def isNull(self):
        return Column(IsNull(self.expr))

    def isNotNull(self):
        return Column(IsNotNull(self.expr))

    def isNaN(self):
        return Column(IsNaN(self.expr))

    def isin(self, *values):
        vals = values[0] if len(values) == 1 and isinstance(
            values[0], (list, tuple)) else values
        return Column(In(self.expr, list(vals)))

    def startswith(self, s: str):
        return Column(StartsWith(self.expr, s))

    def endswith(self, s: str):
        return Column(EndsWith(self.expr, s))

    def contains(self, s: str):
        return Column(Contains(self.expr, s))

    def rlike(self, pattern: str):
        from spark_rapids_tpu.expr.regexexpr import RLike

        return Column(RLike(self.expr, pattern))

    def getField(self, name: str) -> "Column":
        from spark_rapids_tpu.expr.structs import GetStructField

        return Column(GetStructField(self.expr, name), name)

    # sort direction / window

    def asc(self) -> "SortColumn":
        return SortColumn(self.expr, True)

    def desc(self) -> "SortColumn":
        return SortColumn(self.expr, False)

    def asc_nulls_last(self) -> "SortColumn":
        return SortColumn(self.expr, True, nulls_first=False)

    def desc_nulls_first(self) -> "SortColumn":
        return SortColumn(self.expr, False, nulls_first=True)

    def over(self, window_spec) -> "Column":
        from spark_rapids_tpu.expr.windows import WindowExpression

        base = self.expr.children[0] if isinstance(self.expr, Alias) \
            else self.expr
        return Column(WindowExpression(base, window_spec.to_spec_def()))

    def __repr__(self):
        return f"Column<{self.expr!r}>"

    def __hash__(self):
        return id(self)


class SortColumn:
    """Column + sort direction marker (Column.asc()/desc()); consumed by
    orderBy on DataFrame and WindowSpec."""

    def __init__(self, expr, ascending: bool, nulls_first=None):
        self.expr = expr
        self.ascending = ascending
        self.nulls_first = (ascending if nulls_first is None
                            else nulls_first)
