"""DataFrame — the user-facing lazy query surface (pyspark-compatible
subset), building logical plans that TpuOverrides plans onto the device.
"""

from __future__ import annotations

from typing import List, Optional, Union

import pyarrow as pa

from spark_rapids_tpu.api.column import Column
from spark_rapids_tpu.api.functions import UnresolvedColumn
from spark_rapids_tpu.expr import Alias, BoundReference
from spark_rapids_tpu.expr.aggregates import AggregateFunction
from spark_rapids_tpu.expr.core import Expression
from spark_rapids_tpu.plan import logical as L


def _resolve(expr, schema, session=None) -> Expression:
    """Replace UnresolvedColumn markers with BoundReferences; attempt
    UDF bytecode compilation once argument types are concrete."""
    if isinstance(expr, UnresolvedColumn):
        i = _field_index(schema, expr.name)
        f = schema.fields[i]
        return BoundReference(i, f.dataType, f.nullable)
    if isinstance(expr, Expression):
        new_children = [_resolve(c, schema, session)
                        for c in expr.children]
        node = expr.with_children(new_children)
        if getattr(node, "_wants_compile", False):
            from spark_rapids_tpu.config import rapids_conf as _rc
            from spark_rapids_tpu.expr import Cast
            from spark_rapids_tpu.udf import UdfCompileError, compile_udf

            # the OWNING session's conf (fall back to the process
            # active one only when no session is threaded through)
            s = session
            if s is None:
                from spark_rapids_tpu.api.session import TpuSparkSession

                s = TpuSparkSession.active()
            if (s is not None and not
                    s.rapids_conf.get(_rc.UDF_COMPILER_ENABLED)):
                node.compile_error = (
                    "udf compiler disabled via "
                    "spark.rapids.sql.udfCompiler.enabled=false")
                node._wants_compile = False
                return node
            try:
                compiled = compile_udf(node.fn, new_children)
                if compiled.dtype != node.dtype:
                    compiled = Cast(compiled, node.dtype)
                return compiled
            except UdfCompileError as e:
                node.compile_error = str(e)
                node._wants_compile = False
        return node
    raise TypeError(f"cannot resolve {expr!r}")


def _stamp_session(expr: Expression, session) -> Expression:
    """Post-resolution session pass: stamp the session timezone on
    tz-aware nodes (GpuTimeZoneDB role — the zone becomes part of every
    jit key) and pin current_date/current_timestamp to one literal per
    query (Spark's QueryExecution does the same)."""
    from spark_rapids_tpu.config import rapids_conf as rc
    from spark_rapids_tpu.expr.cast import Cast
    from spark_rapids_tpu.expr.datetimes import (
        CurrentDate,
        CurrentTimestamp,
        TzAware,
    )

    tz = session.rapids_conf.get(rc.SESSION_TZ) if session else "UTC"

    def fn(node):
        if isinstance(node, (TzAware, Cast, CurrentDate,
                             CurrentTimestamp)):
            node.tz = tz  # node is a fresh copy from transform()
        return node

    return expr.transform(fn)


def _pin_query_time(plan):
    """Replace current_date/current_timestamp markers with ONE literal
    per query (Spark pins both at query start), applied at physical
    planning time."""
    import time

    import numpy as np

    from spark_rapids_tpu.expr.core import Literal
    from spark_rapids_tpu.expr.datetimes import (
        CurrentDate,
        CurrentTimestamp,
    )
    from spark_rapids_tpu.ops import tzdb
    from spark_rapids_tpu.sqltypes.datatypes import (
        date as date_t,
        timestamp as timestamp_t,
    )

    now_us = int(time.time() * 1_000_000)

    def efn(node):
        if isinstance(node, CurrentTimestamp):
            return Literal(now_us, timestamp_t)
        if isinstance(node, CurrentDate):
            local = int(tzdb.utc_to_local_np(
                np.array([now_us], np.int64),
                getattr(node, "tz", "UTC"))[0])
            return Literal(local // 86_400_000_000, date_t)
        return node

    return L.transform_expressions(plan, lambda e: e.transform(efn))


def _field_index(schema, name: str) -> int:
    lowered = [n.lower() for n in schema.names]
    if name in schema.names:
        return schema.names.index(name)
    if name.lower() in lowered:
        return lowered.index(name.lower())
    raise KeyError(f"column {name!r} not in {schema.names}")


def _named(expr: Expression, fallback: str) -> Alias:
    if isinstance(expr, Alias):
        return expr
    return Alias(expr, fallback)


class DataFrame:
    def __init__(self, plan: L.LogicalPlan, session):
        self._plan = plan
        self.session = session

    # --- schema ---

    @property
    def schema(self):
        return self._plan.schema

    @property
    def columns(self) -> List[str]:
        return self._plan.schema.names

    def __getitem__(self, name: str) -> Column:
        i = _field_index(self.schema, name)
        f = self.schema.fields[i]
        ref = BoundReference(i, f.dataType, f.nullable)
        # provenance for join-condition resolution (df1.a == df2.b)
        ref._origin_plan = self._plan
        return Column(ref, name)

    # --- transformations ---

    def _col_expr(self, c) -> Expression:
        if isinstance(c, str):
            return _stamp_session(self[c].expr, self.session)
        if isinstance(c, Column):
            return _stamp_session(
                _resolve(c.expr, self.schema, self.session),
                self.session)
        raise TypeError(repr(c))

    def select(self, *cols) -> "DataFrame":
        exprs = []
        for i, c in enumerate(cols):
            if isinstance(c, str) and c == "*":
                for j, f in enumerate(self.schema.fields):
                    exprs.append(Alias(BoundReference(j, f.dataType,
                                                      f.nullable), f.name))
                continue
            name = c if isinstance(c, str) else c.name
            e = self._col_expr(c)
            exprs.append(_named(e, name if isinstance(name, str)
                                else f"col{i}"))
        return self._finish_project(exprs)

    def _finish_project(self, exprs: List[Alias]) -> "DataFrame":
        """Emit Project, extracting window expressions into Window nodes
        and generators into Generate nodes first (Spark's
        ExtractWindowExpressions / ExtractGenerator rules)."""
        from spark_rapids_tpu.expr.generators import (
            Explode,
            PosExplode,
            contains_generator,
        )
        from spark_rapids_tpu.expr.windows import (
            WindowExpression,
            contains_window,
        )

        if any(contains_generator(e) for e in exprs):
            if any(contains_window(e) for e in exprs):
                raise ValueError(
                    "explode combined with window expressions in one "
                    "select is not supported; materialize the window "
                    "column with a prior select first")
            gens = [e for e in exprs
                    if isinstance(e.children[0], Explode)]
            others = [e for e in exprs
                      if not isinstance(e.children[0], Explode)]
            if len(gens) != 1 or any(contains_generator(e)
                                     for e in others):
                raise ValueError(
                    "exactly one top-level explode/posexplode per "
                    "select (Spark's one-generator rule)")
            gen = gens[0]
            plan = L.Generate(others, gen, self._plan,
                              position=isinstance(gen.children[0],
                                                  PosExplode))
            return DataFrame(plan, self.session)

        if not any(contains_window(e) for e in exprs):
            return DataFrame(L.Project(exprs, self._plan), self.session)
        plan = self._plan
        n_base = len(plan.schema.fields)
        groups = {}  # sort_key -> [Alias(WindowExpression)]
        for e in exprs:
            base = e.children[0]
            if isinstance(base, WindowExpression):
                groups.setdefault(base.spec.sort_key(), []).append(e)
            elif contains_window(e):
                raise NotImplementedError(
                    "window expressions must be top-level in v1 "
                    "(wrap arithmetic around them in a second select)")
        appended = {}
        ordinal = n_base
        for key, aliases in groups.items():
            plan = L.Window(aliases, plan)
            for a in aliases:
                appended[id(a)] = ordinal
                ordinal += 1
        out = []
        for e in exprs:
            if id(e) in appended:
                out.append(Alias(
                    BoundReference(appended[id(e)], e.dtype, True), e.name))
            else:
                out.append(e)
        return DataFrame(L.Project(out, plan), self.session)

    def withColumn(self, name: str, c: Column) -> "DataFrame":
        exprs = []
        replaced = False
        for j, f in enumerate(self.schema.fields):
            if f.name == name:
                exprs.append(Alias(self._col_expr(c), name))
                replaced = True
            else:
                exprs.append(Alias(BoundReference(j, f.dataType, f.nullable),
                                   f.name))
        if not replaced:
            exprs.append(Alias(self._col_expr(c), name))
        return self._finish_project(exprs)

    def withColumnRenamed(self, old: str, new: str) -> "DataFrame":
        exprs = []
        for j, f in enumerate(self.schema.fields):
            exprs.append(Alias(BoundReference(j, f.dataType, f.nullable),
                               new if f.name == old else f.name))
        return DataFrame(L.Project(exprs, self._plan), self.session)

    def drop(self, *names) -> "DataFrame":
        keep = [f.name for f in self.schema.fields if f.name not in names]
        return self.select(*keep)

    def filter(self, condition) -> "DataFrame":
        from spark_rapids_tpu.expr.windows import contains_window

        if isinstance(condition, str):
            raise NotImplementedError("SQL string filters: use Column")
        cond = self._col_expr(condition)
        if contains_window(cond):
            raise ValueError(
                "window functions are not allowed in filter conditions; "
                "materialize with select/withColumn first (Spark analysis "
                "rule)")
        return DataFrame(L.Filter(cond, self._plan), self.session)

    where = filter

    def groupBy(self, *cols) -> "GroupedData":
        return GroupedData(self, list(cols))

    def rollup(self, *cols) -> "GroupedData":
        """GROUP BY ROLLUP — hierarchical subtotal grouping sets
        (lowered through Expand, like Spark's rollup plan)."""
        return GroupedData(self, list(cols), mode="rollup")

    def cube(self, *cols) -> "GroupedData":
        """GROUP BY CUBE — all 2^n grouping-set combinations."""
        return GroupedData(self, list(cols), mode="cube")

    def groupingSets(self, sets, *cols) -> "GroupedData":
        """Explicit grouping sets: `sets` is a list of lists of column
        names drawn from `cols`."""
        return GroupedData(self, list(cols), mode="grouping_sets",
                           sets=sets)

    def agg(self, *cols) -> "DataFrame":
        return GroupedData(self, []).agg(*cols)

    def mapInPandas(self, fn, schema) -> "DataFrame":
        """Iterator-of-pandas-frames transform through the Arrow worker
        pool (GpuMapInPandasExec role). `schema` is a DDL string
        ('a long, b double') or StructType."""
        from spark_rapids_tpu.sqltypes.datatypes import parse_ddl_schema

        return DataFrame(
            L.MapInPandas(fn, parse_ddl_schema(schema), self._plan),
            self.session)

    def sample(self, withReplacement=None, fraction=None,
               seed=None) -> "DataFrame":
        """Bernoulli row sample (pyspark-compatible overloads:
        sample(fraction), sample(fraction, seed),
        sample(withReplacement, fraction, seed))."""
        if isinstance(withReplacement, float):
            # sample(fraction[, seed]) form
            withReplacement, fraction, seed = False, withReplacement, \
                fraction
        if fraction is None:
            raise ValueError("sample() requires a fraction")
        if not 0.0 <= float(fraction) <= 1.0 and not withReplacement:
            raise ValueError(f"fraction must be in [0, 1]: {fraction}")
        if seed is None:
            import random

            seed = random.randint(0, 2 ** 31 - 1)
        return DataFrame(
            L.Sample(float(fraction), int(seed), bool(withReplacement),
                     self._plan),
            self.session)

    def crossJoin(self, other: "DataFrame") -> "DataFrame":
        return DataFrame(
            L.Join(self._plan, other._plan, "cross", [], []), self.session)

    def _resolve_combined(self, other: "DataFrame", e) -> Expression:
        """Resolve an expression against [left fields | right fields]:
        UnresolvedColumn binds left-first; BoundReferences originating
        from `other` (df2["x"]) shift into the right half."""
        n_l = len(self.schema.fields)

        def go(node):
            if isinstance(node, UnresolvedColumn):
                try:
                    i = _field_index(self.schema, node.name)
                    f = self.schema.fields[i]
                    return BoundReference(i, f.dataType, f.nullable)
                except KeyError:
                    i = _field_index(other.schema, node.name)
                    f = other.schema.fields[i]
                    return BoundReference(n_l + i, f.dataType, f.nullable)
            if isinstance(node, BoundReference):
                org = getattr(node, "_origin_plan", None)
                if org is other._plan:
                    return BoundReference(node.ordinal + n_l, node.dtype,
                                          node.nullable)
                if org is None or org is self._plan:
                    return node
                raise ValueError(
                    "join condition references a column from a DataFrame "
                    "that is neither side of this join; re-derive it from "
                    "the joined inputs (e.g. use the filtered/projected "
                    "DataFrame's own columns)")
            if isinstance(node, Expression):
                return node.with_children([go(c) for c in node.children])
            raise TypeError(f"cannot resolve {node!r}")

        return _stamp_session(go(e), self.session)

    @staticmethod
    def _promote_keys(lk, rk):
        """Implicit numeric promotion of mismatched key types
        (Spark's ImplicitTypeCasts)."""
        from spark_rapids_tpu.expr import Cast
        from spark_rapids_tpu.sqltypes import NumericType
        from spark_rapids_tpu.sqltypes.datatypes import numeric_promotion

        out_l, out_r = [], []
        for a, b in zip(lk, rk):
            if a.dtype != b.dtype:
                if isinstance(a.dtype, NumericType) and isinstance(
                        b.dtype, NumericType):
                    common = numeric_promotion(a.dtype, b.dtype)
                    a = a if a.dtype == common else Cast(a, common)
                    b = b if b.dtype == common else Cast(b, common)
                else:
                    raise TypeError(
                        f"join key type mismatch: {a.dtype} vs {b.dtype}")
            out_l.append(a)
            out_r.append(b)
        return out_l, out_r

    @staticmethod
    def _split_conjuncts(e: Expression) -> List[Expression]:
        from spark_rapids_tpu.expr import And

        if isinstance(e, And):
            return (DataFrame._split_conjuncts(e.children[0]) +
                    DataFrame._split_conjuncts(e.children[1]))
        return [e]

    def _extract_equi_keys(self, cond: Expression):
        """Spark's ExtractEquiJoinKeys: pull EqualTo conjuncts whose
        sides reference only one input each; remainder stays a
        condition."""
        from spark_rapids_tpu.expr import And, EqualTo

        n_l = len(self.schema.fields)
        lk, rk, rest = [], [], []
        for c in self._split_conjuncts(cond):
            if isinstance(c, EqualTo):
                a, b = c.children
                ra, rb = a.references(), b.references()
                if ra and rb:
                    if max(ra) < n_l <= min(rb):
                        lk.append(a)
                        rk.append(b)
                        continue
                    if max(rb) < n_l <= min(ra):
                        lk.append(b)
                        rk.append(a)
                        continue
            rest.append(c)
        from spark_rapids_tpu.exec.joins import remap_refs

        rk = [remap_refs(k, lambda o: o - n_l) for k in rk]
        remainder = None
        for c in rest:
            remainder = c if remainder is None else And(remainder, c)
        return lk, rk, remainder

    def join(self, other: "DataFrame", on=None, how: str = "inner"
             ) -> "DataFrame":
        how = {"outer": "full", "full_outer": "full", "leftouter": "left",
               "rightouter": "right", "leftsemi": "left_semi",
               "semi": "left_semi", "leftanti": "left_anti",
               "anti": "left_anti"}.get(how, how)
        if on is None:
            if how not in ("inner", "cross"):
                raise ValueError(
                    f"join type {how!r} requires join keys or a condition")
            return self.crossJoin(other)
        if how == "cross":
            # keys given: Spark treats cross-with-keys as an equi join
            how = "inner"
        if isinstance(on, str):
            on = [on]
        if isinstance(on, Column) or isinstance(on, Expression):
            cond = self._resolve_combined(
                other, on.expr if isinstance(on, Column) else on)
            lk, rk, remainder = self._extract_equi_keys(cond)
            lk, rk = self._promote_keys(lk, rk)
            jt = "cross" if not lk and remainder is None else how
            plan = L.Join(self._plan, other._plan, jt, lk, rk,
                          condition=remainder)
            return DataFrame(plan, self.session)
        if isinstance(on, (list, tuple)) and on and isinstance(on[0], str):
            lk = [self[c].expr for c in on]
            rk = [other[c].expr for c in on]
        else:
            raise TypeError(
                "join `on` must be column name(s) or a Column expression")
        # name-keyed joins rewrite mismatched key columns to the common
        # type in place (the joined output carries the promoted type,
        # matching Spark's ImplicitTypeCasts on USING joins)
        plk, prk = self._promote_keys(lk, rk)
        df_l, df_r = self, other
        if any(p is not o for p, o in zip(plk, lk)):
            for i, (p, o) in enumerate(zip(plk, lk)):
                if p is not o:
                    df_l = df_l.withColumn(on[i], Column(p))
            lk = [df_l[c].expr for c in on]
        if any(p is not o for p, o in zip(prk, rk)):
            for i, (p, o) in enumerate(zip(prk, rk)):
                if p is not o:
                    df_r = df_r.withColumn(on[i], Column(p))
            rk = [df_r[c].expr for c in on]
        plan = L.Join(df_l._plan, df_r._plan, how, lk, rk)
        return DataFrame(plan, self.session)

    def union(self, other: "DataFrame") -> "DataFrame":
        return DataFrame(L.Union([self._plan, other._plan]), self.session)

    unionAll = union

    def orderBy(self, *cols, ascending=None) -> "DataFrame":
        from spark_rapids_tpu.api.column import SortColumn
        from spark_rapids_tpu.expr.windows import contains_window

        orders = []
        asc_list = (ascending if isinstance(ascending, (list, tuple))
                    else [ascending] * len(cols))
        for c, asc in zip(cols, asc_list):
            if isinstance(c, SortColumn):
                orders.append(L.SortOrder(
                    _stamp_session(
                        _resolve(c.expr, self.schema, self.session),
                        self.session),
                    c.ascending, c.nulls_first))
                continue
            a = True if asc is None else bool(asc)
            orders.append(L.SortOrder(self._col_expr(c), a))
        for o in orders:
            if contains_window(o.expr):
                raise ValueError(
                    "window functions are not allowed in orderBy; "
                    "materialize with select/withColumn first")
        return DataFrame(L.Sort(orders, self._plan, global_sort=True),
                         self.session)

    sort = orderBy

    def limit(self, n: int) -> "DataFrame":
        return DataFrame(L.Limit(n, self._plan), self.session)

    def distinct(self) -> "DataFrame":
        return self.groupBy(*self.columns).agg()

    def repartition(self, n: int, *cols) -> "DataFrame":
        keys = [self._col_expr(c) for c in cols] or None
        return DataFrame(L.Repartition(self._plan, n, keys), self.session)

    # --- actions ---

    def _physical(self, cpu_oracle: bool = False):
        from spark_rapids_tpu.config import rapids_conf as rc
        from spark_rapids_tpu.plan.optimizer import optimize
        from spark_rapids_tpu.plan.overrides import plan_query

        if cpu_oracle:
            # data-shape fallback: all-CPU plan from the ORIGINAL
            # logical tree — substituting device-cached relations here
            # would re-materialize them on device and re-raise the very
            # condition (e.g. StringWidthExceeded) being fallen back
            # from
            plan = _pin_query_time(self._plan)
            conf = rc.RapidsConf({
                **self.session._settings,
                "spark.rapids.tpu.test.cpuOracle": True})
            return plan_query(optimize(plan), conf)
        # serve registered device-cached subtrees from their entries
        # (Spark CacheManager.useCachedData role) BEFORE time pinning:
        # pinning may rebuild nodes, which would break identity matching
        plan = self.session.cache_manager.substitute(self._plan)
        plan = _pin_query_time(plan)
        return plan_query(optimize(plan), self.session.rapids_conf)

    # --- caching ---
    #
    # Two tiers, mirroring the reference's split:
    # - host (default): the ParquetCachedBatchSerializer analog — this
    #   DataFrame's RESULT as a compressed parquet blob, returned on
    #   re-collect.
    # - device: the CacheManager/InMemoryRelation analog
    #   (exec/relation_cache.py) — the RELATION as HBM-resident
    #   spillable batches; any DERIVED query serves its scan from HBM
    #   (no decode, no host->device link traffic). The TPU-native tier:
    #   tunneled links make re-upload the dominant cost.

    def cache(self, storage: str = "host") -> "DataFrame":
        if storage == "device":
            self.session.cache_manager.register(
                self._plan, self.session.rapids_conf)
        elif storage == "host":
            self._cached = True
        else:
            raise ValueError(
                f"unknown cache storage {storage!r}: use 'host' "
                "(result blob) or 'device' (HBM-resident relation)")
        return self

    def persist(self, storage="host", *_a, **_k) -> "DataFrame":
        # PySpark callers pass a StorageLevel positionally; anything
        # non-string maps to the host tier.
        if not isinstance(storage, str):
            storage = "host"
        return self.cache(storage)

    def unpersist(self) -> "DataFrame":
        self._cached = False
        self._cache_blob = None
        self.session.cache_manager.unregister(self._plan)
        return self

    def _cache_store(self, table: pa.Table):
        import io as _io

        import pyarrow.parquet as pq

        buf = _io.BytesIO()
        pq.write_table(table, buf, compression="snappy")
        self._cache_blob = buf.getvalue()

    def _cache_load(self) -> Optional[pa.Table]:
        blob = getattr(self, "_cache_blob", None)
        if blob is None:
            return None
        import io as _io

        import pyarrow.parquet as pq

        return pq.read_table(_io.BytesIO(blob))

    def collect_arrow(self) -> pa.Table:
        from spark_rapids_tpu.config import rapids_conf as rc
        from spark_rapids_tpu.runtime import admission, device_monitor
        from spark_rapids_tpu.runtime.errors import (
            DeadlockDetectedError,
            DeviceLostError,
        )

        try:
            return self._collect_arrow_admitted()
        except DeviceLostError:
            # this query was unwound by device-loss fencing
            # (runtime/device_monitor.py): its permits/buffers/slot are
            # released, warm recovery rebuilds the backend and bumps
            # the device epoch, and ONE resubmission through admission
            # re-runs the query against the fresh backend (the
            # retryVictim pattern). Outermost collect only; the wait
            # for the fence to lift is bounded by
            # device.recovery.timeoutMs.
            mon = device_monitor.get()
            if admission.current_handle() is not None or \
                    not mon.resubmit or \
                    not self.session.rapids_conf.get(
                        rc.DEVICE_RECOVERY_RESUBMIT):
                raise
            if not mon.await_ready():
                raise  # recovery itself is wedged — surface the loss
            mon.note_resubmit()
            return self._collect_arrow_admitted()
        except DeadlockDetectedError:
            # this query was unwound as a deadlock victim
            # (runtime/sanitizer.py): every permit/buffer/slot it held
            # is released, so a single resubmission through admission
            # serializes behind the cycle's survivors and completes.
            # Only the OUTERMOST collect retries (a nested collect's
            # error belongs to the outer query's token), and only once
            # — a second cycle means something is systemically wedged
            # and the caller should see it.
            if admission.current_handle() is not None or \
                    not self.session.rapids_conf.get(
                        rc.SANITIZER_VICTIM_RETRY):
                raise
            return self._collect_arrow_admitted()

    def _collect_arrow_admitted(self) -> pa.Table:
        # Engine-selection record (GpuOverrides NOT_ON_GPU diagnostics
        # discipline applied to whole-query engine dispatch): which
        # engine ran, and why each faster engine was skipped. Surfaced
        # via explain() and session.query_metrics — a fused/mesh compile
        # error must never silently land a query on the dispatch-bound
        # eager path.
        from spark_rapids_tpu.obs import events as obs_events
        from spark_rapids_tpu.runtime import admission

        rec = {"engine": None, "fallbacks": [], "compile": None,
               "degradations": [], "scheduler": None}
        self._last_exec = rec
        self.session.last_execution = rec
        # admission front door (runtime/admission.py): the OUTERMOST
        # collect takes a query slot (possibly queueing, possibly shed
        # with QueryRejectedError before any work), owns the query's
        # CancelToken for the whole execution, and releases the slot on
        # exit; nested collects ride the enclosing query's handle
        scope = admission.AdmissionScope(
            self.session, description=type(self._plan).__name__)
        with scope as handle:
            # the query scope brackets the event stream (query.start /
            # query.end frame the event log + span tree); nested
            # collects fold into the outer query's stream
            qid = obs_events.begin_query(handle.query_id)
            rec["queryId"] = qid
            rec["admission"] = {"queueWaitMs": handle.queue_wait_ms,
                                "priority": handle.priority}
            if not scope.nested and handle.queue_wait_ms:
                # queue wait on the query's span tree (no task scope
                # here, so the span hangs off the query root)
                obs_events.emit(
                    "operator.span", operator="AdmissionQueue",
                    metric="queueWaitMs",
                    wallNs=int(handle.queue_wait_ms * 1_000_000),
                    deviceNs=0)
            import time as _time

            from spark_rapids_tpu.obs import telemetry as _tel

            t0 = _time.perf_counter()
            out_rows = None
            try:
                out = self._collect_arrow_traced(rec)
                out_rows = out.num_rows
                return out
            finally:
                # data-movement report for this query: the transfer
                # ledger's per-query view + roofline fractions over the
                # measured wall time. The OUTERMOST scope owns the
                # summary event (nested collects would snapshot the
                # same qid mid-flight); every rec still carries the
                # view so callers see bytes for their slice too.
                tel = _tel.query_summary(
                    qid, wall_s=_time.perf_counter() - t0,
                    output_rows=out_rows)
                rec["telemetry"] = tel or None
                if tel and not scope.nested:
                    _tel.ledger.finalize_query(qid, tel)
                    obs_events.emit(
                        "telemetry.summary",
                        bytesMoved=tel.get("bytesMoved"),
                        bytesMovedTotal=tel.get("bytesMovedTotal"),
                        hbmPeakBytes=tel.get("hbmPeakBytes"),
                        rooflineFrac=tel.get("rooflineFrac"),
                        linkFrac=tel.get("linkFrac"),
                        bytesPerOutputRow=tel.get("bytesPerOutputRow"),
                        wallMs=tel.get("wallMs"))
                obs_events.finish_query(
                    qid, engine=rec["engine"],
                    status="ok" if rec["engine"] is not None
                    else "error",
                    fallbacks=len(rec["fallbacks"]),
                    degradations=len(rec["degradations"]))

    def _collect_arrow_traced(self, rec) -> pa.Table:
        from spark_rapids_tpu.obs import events as obs_events

        def ran(engine: str, out: pa.Table, store: bool = True
                ) -> pa.Table:
            rec["engine"] = engine
            self.session.query_metrics.metric("engine." + engine).add(1)
            if store and getattr(self, "_cached", False):
                self._cache_store(out)
            return out

        def fell_back(engine: str, reason: str) -> None:
            rec["fallbacks"].append((engine, reason))
            self.session.query_metrics.metric(
                "engineFallback." + engine).add(1)

        cached = self._cache_load()
        if cached is not None:
            return ran("hostCache", cached, store=False)

        phys, meta = self._physical()
        # structured twin of the NOT_ON_TPU explain: one placement
        # event per plan node, with the verbatim fallback reason —
        # what obs.report.qualification() reads
        obs_events.emit_plan_placement(meta)
        if self.session.rapids_conf.is_explain_only:
            return pa.table({})
        from spark_rapids_tpu.runtime import compile_cache as _cc
        from spark_rapids_tpu.runtime.errors import StringWidthExceeded

        from spark_rapids_tpu.runtime import scheduler as _sched

        # Compile observability (the tentpole's watch-forever channel):
        # the process compile ledger is snapshotted around the query and
        # the delta — programs compiled, structural cache hits, warmup
        # hits, compile seconds — lands in last_execution["compile"]
        # and the session metrics, with the fused engine's distinct
        # program-variant count folded in when it ran. The stage
        # scheduler's ledger (tasks launched/retried/speculated,
        # recomputed partitions, evicted workers) rides the same
        # snapshot-delta channel into last_execution["scheduler"].
        before = _cc.stats.snapshot()
        sched_before = _sched.stats.snapshot()
        try:
            return self._dispatch_engines(phys, ran, fell_back, rec)
        except StringWidthExceeded as e:
            # DATA-shape fallback: a string column's longest value
            # exceeds the device padded-width ceiling — re-plan on the
            # CPU engine, recorded like any other fallback (the
            # "anything unsupported falls back with a reason" planner
            # invariant extended to data-dependent shapes)
            fell_back("device", str(e))
            phys_cpu, _ = self._physical(cpu_oracle=True)
            return ran("cpu", phys_cpu.collect())
        finally:
            comp = _cc.stats.delta(before, _cc.stats.snapshot())
            comp["variantCount"] = rec.pop("_fused_variants", None)
            rec["compile"] = comp
            qm = self.session.query_metrics
            qm.metric("compile.programsCompiled").add(
                comp["programsCompiled"])
            qm.metric("compile.cacheHits").add(comp["cacheHits"])
            qm.metric("compile.warmHits").add(comp["warmHits"])
            qm.metric("compile.timeMs").add(
                int(comp["compileSeconds"] * 1000))
            qm.metric("compile.artifactsQuarantined").add(
                comp.get("artifactsQuarantined", 0))
            sch = _sched.stats.delta(sched_before,
                                     _sched.stats.snapshot())
            rec["scheduler"] = sch
            for key in ("tasksLaunched", "tasksRetried",
                        "tasksSpeculated", "speculativeWins",
                        "recomputedPartitions", "evictedWorkers"):
                if sch.get(key):
                    qm.metric("scheduler." + key).add(sch[key])

    def _dispatch_engines(self, phys, ran, fell_back, rec) -> pa.Table:
        """Engine dispatch with the DEGRADATION LADDER (PR 2):
        mesh/fused compile errors fall back as before (a missing
        lowering is structural), but execution FAILURES — terminal
        OOMs, injected device.dispatch faults — demote down the ladder
        fused -> eager -> CPU, each demotion recorded in
        rec["degradations"] and the degrade.* metrics. A per-program-key
        circuit breaker (runtime/degrade.py) stops re-trying the fused
        engine on a plan that keeps dying there."""
        from spark_rapids_tpu.config import rapids_conf as rc
        from spark_rapids_tpu.runtime import cancellation, degrade, faults
        from spark_rapids_tpu.runtime.errors import TpuOOMError

        conf = self.session.rapids_conf
        ladder_on = conf.get(rc.DEGRADE_ENABLED)
        qm = self.session.query_metrics
        # ladder rungs are yield points: a cancelled/expired query must
        # not start the next (slower) engine
        cancellation.check_current()

        def demoted(frm: str, to: str, reason: str) -> None:
            rec["degradations"].append(
                {"from": frm, "to": to, "reason": reason})
            degrade.record_demotion(f"{frm}To{to.capitalize()}",
                                    frm=frm, to=to, reason=reason)
            qm.metric(f"degrade.{frm}To{to.capitalize()}").add(1)

        from spark_rapids_tpu.runtime import device_monitor as _dm

        mon = _dm.get()
        if mon.enabled and mon.fenced and ladder_on:
            # engine FENCED for device-loss recovery: the device rungs
            # are down, but the service is not — serve this query on
            # the CPU rung (the PR 2 degrade discipline), recorded
            # like any other demotion
            demoted("fused", "cpu",
                    f"device fenced for recovery (epoch {mon.epoch}): "
                    f"serving on the CPU rung")
            phys_cpu, _ = self._physical(cpu_oracle=True)
            return ran("cpu", phys_cpu.collect())

        mesh_n = conf.get(rc.MESH_SIZE)
        if not mesh_n and conf.get(rc.SHUFFLE_MODE) == "ICI":
            # ICI shuffle == the SPMD mesh engine over every local chip
            import jax

            mesh_n = len(jax.devices())
        if mesh_n:
            from spark_rapids_tpu.parallel.plan_compiler import (
                MeshCompileError,
                MeshQueryExecutor,
            )

            try:
                return ran("mesh", MeshQueryExecutor.for_devices(
                    mesh_n, conf).execute(phys))
            except MeshCompileError as e:
                # operator without a mesh lowering: thread-pool path
                fell_back("mesh", str(e))
        skip_fused = False
        if conf.get(rc.STREAM_ENABLED):
            from spark_rapids_tpu.runtime.errors import DeviceLostError
            from spark_rapids_tpu.stream import (
                StreamCompileError,
                StreamExecutor,
                stream_selected,
            )

            if stream_selected(phys, conf):
                # a scan's working set exceeds the window quota of free
                # HBM: the resident engines would OOM or thrash, so the
                # out-of-core rung runs FIRST for this plan
                try:
                    return ran("stream", StreamExecutor(conf)
                               .execute(phys))
                except StreamCompileError as e:
                    # selected scan has no streamable prefix worth
                    # running: structural, not a failure
                    fell_back("stream", str(e))
                except DeviceLostError:
                    # mid-stream device loss: retired partitions are
                    # lineage-cached; the outermost collect's one-shot
                    # resubmit resumes the stream past them
                    raise
                except (TpuOOMError, faults.InjectedFault) as e:
                    if not ladder_on:
                        raise
                    demoted("stream", "eager",
                            f"{type(e).__name__}: {e}")
                    # this plan was SELECTED because its working set
                    # exceeds HBM — the fused rung would refuse it at
                    # the same gate, so demote straight to eager
                    skip_fused = True
        if conf.get(rc.FUSED_EXEC) and not skip_fused:
            from spark_rapids_tpu.exec.fused import (
                FusedCompileError,
                FusedSingleChipExecutor,
            )

            fkey = degrade.plan_fingerprint(phys)
            breaker = degrade.breaker()
            if conf.get(rc.OOM_INJECTION_MODE) != "none":
                # the forced-OOM harness targets eager allocation
                # points; fused inputs route through the eager path
                # (satellite of the fused.py:453 crash replacement)
                degrade.record_demotion("fusedOomInjectionFallback")
                qm.metric("degrade.fusedOomInjectionFallback").add(1)
                demoted("fused", "eager",
                        "OOM injection targets the eager engine's "
                        "allocation points")
            elif ladder_on and not breaker.allow(fkey):
                degrade.record_demotion("breakerShortCircuit")
                qm.metric("degrade.breakerShortCircuit").add(1)
                demoted("fused", "eager",
                        f"circuit breaker open after "
                        f"{breaker.threshold} consecutive fused "
                        f"failures for this program key")
            else:
                ex = FusedSingleChipExecutor(conf)
                try:
                    out = ex.execute(phys)
                    if ex.last_compile_metrics is not None:
                        rec["_fused_variants"] = \
                            ex.last_compile_metrics["variantCount"]
                    breaker.record_success(fkey)
                    return ran("fused", out)
                except FusedCompileError as e:
                    # no fused lowering / too big: per-operator engine
                    # (structural, not a failure — no breaker state)
                    fell_back("fused", str(e))
                except (TpuOOMError, faults.InjectedFault) as e:
                    if not ladder_on:
                        raise
                    n = breaker.record_failure(fkey)
                    demoted("fused", "eager",
                            f"{type(e).__name__}: {e} "
                            f"(failure {n}/{breaker.threshold} for "
                            f"this program key)")
        try:
            cancellation.check_current()
            if conf.get(rc.ADAPTIVE_ENABLED):
                from spark_rapids_tpu.exec.operators import (
                    TpuShuffleExchangeExec,
                )
                from spark_rapids_tpu.plan.aqe import (
                    AdaptiveQueryExecutor,
                )

                def has_exchange(n):
                    return isinstance(n, TpuShuffleExchangeExec) or any(
                        has_exchange(c) for c in n.children)

                if has_exchange(phys):
                    faults.maybe_inject("device.dispatch", detail="aqe")
                    with _dm.guard("eager.dispatch", detail="aqe",
                                   inject=True):
                        return ran("aqe", AdaptiveQueryExecutor(
                            conf).execute(phys))
            faults.maybe_inject("device.dispatch", detail="eager")
            # fatal-classification + chaos site device.fatal around the
            # per-operator engine: a dead backend fences for warm
            # recovery (DeviceLostError rides past the ladder — slow
            # beats dead does not apply to a resubmittable loss)
            with _dm.guard("eager.dispatch", detail="eager",
                           inject=True):
                return ran("eager", phys.collect())
        except (TpuOOMError, faults.InjectedFault) as e:
            if not ladder_on:
                raise
            cancellation.check_current()
            # last rung: the CPU engine (exec/cpu_eval.py lowering via
            # the cpu-oracle plan) — slow beats dead
            demoted("eager", "cpu", f"{type(e).__name__}: {e}")
            phys_cpu, _ = self._physical(cpu_oracle=True)
            return ran("cpu", phys_cpu.collect())

    def collect(self) -> List[tuple]:
        t = self.collect_arrow()
        names = t.column_names
        cols = [t.column(i).to_pylist() for i in range(t.num_columns)]
        return [Row(zip(names, vals)) for vals in zip(*cols)] if cols \
            else []

    def toPandas(self):
        return self.collect_arrow().to_pandas()

    def count(self) -> int:
        from spark_rapids_tpu.api import functions as F

        agg_df = self.agg(F.count("*").alias("count"))
        return agg_df.collect_arrow().column("count").to_pylist()[0]

    def show(self, n: int = 20, truncate: bool = True):
        print(self.limit(n).toPandas().to_string(index=False))

    def explain(self, extended: bool = False):
        phys, meta = self._physical()
        rec0 = getattr(self, "_last_exec", None)
        if rec0 is not None and rec0.get("engine") == "mesh":
            # re-derive the mesh planner's exchange-transport choice on
            # this fresh plan so pretty() shows [strategy=ici]
            from spark_rapids_tpu.parallel.plan_compiler import (
                stamp_exchange_strategies,
            )

            stamp_exchange_strategies(phys, self.session.rapids_conf)
        if rec0 is not None and rec0.get("engine") == "stream":
            # re-derive the streaming selection on this fresh plan so
            # pretty() shows TpuFileScanExec [strategy=stream]
            from spark_rapids_tpu.stream import stamp_stream_strategy

            stamp_stream_strategy(phys, self.session.rapids_conf)
        print("== Physical Plan ==")
        print(phys.pretty())
        if extended:
            print("== Device Placement ==")
            print(meta.explain(only_not_on_device=False))
        rec = getattr(self, "_last_exec", None)
        if rec is not None and rec["engine"] is not None:
            print("== Engine ==")
            print(rec["engine"])
            for eng, reason in rec["fallbacks"]:
                print(f"  fell back from {eng}: {reason}")
            for d in rec.get("degradations", []):
                print(f"  degraded {d['from']} -> {d['to']}: "
                      f"{d['reason']}")
            sch = rec.get("scheduler") or {}
            if sch.get("tasksLaunched"):
                detail = ", ".join(
                    f"{sch[k]} {label}" for k, label in (
                        ("tasksRetried", "retried"),
                        ("tasksSpeculated", "speculated"),
                        ("recomputedPartitions", "recomputed"),
                        ("evictedWorkers", "workers evicted"))
                    if sch.get(k))
                print(f"  scheduler: {sch['tasksLaunched']} task "
                      f"attempts" + (f" ({detail})" if detail else ""))

    def write_parquet(self, path: str):
        self.session.write_parquet(self, path)

    @property
    def write(self) -> "DataFrameWriter":
        return DataFrameWriter(self)


class DataFrameWriter:
    """df.write.format(...).mode(...).partitionBy(...).save(path) — the
    columnar write path (ColumnarOutputWriter / GpuFileFormatDataWriter
    roles, io/writers.py) plus the Delta Lake commit protocol
    (lakehouse/delta.py)."""

    def __init__(self, df: DataFrame):
        self._df = df
        self._format = "parquet"
        self._mode = "error"
        self._partition_by: List[str] = []
        self._options: dict = {}

    def format(self, fmt: str) -> "DataFrameWriter":
        self._format = fmt
        return self

    def mode(self, m: str) -> "DataFrameWriter":
        self._mode = {"errorifexists": "error"}.get(m, m)
        return self

    def option(self, k, v) -> "DataFrameWriter":
        self._options[k] = v
        return self

    def partitionBy(self, *cols) -> "DataFrameWriter":
        self._partition_by = list(cols)
        return self

    def save(self, path: str):
        """Transactional save: the whole write — the reading collect
        included — runs inside ONE query scope, so write.* events and
        the telemetry `write` block attribute to the same queryId the
        read side reported under."""
        from spark_rapids_tpu.obs import events as obs_events

        qid = obs_events.begin_query()
        status = "error"
        try:
            if self._format == "delta":
                from spark_rapids_tpu.lakehouse.delta import write_delta

                # delta.* writer options become table properties
                props = {k: str(v) for k, v in self._options.items()
                         if k.startswith("delta.")}
                write_delta(self._df, path, mode=self._mode,
                            partition_by=self._partition_by,
                            properties=props or None)
                status = "ok"
                return
            out = self._save_committed(path, qid)
            status = "ok"
            return out
        finally:
            obs_events.finish_query(qid, engine=None, status=status,
                                    fallbacks=0, degradations=0)

    def _save_committed(self, path: str, qid: int):
        """File-format save through the two-phase commit protocol
        (io/commit.py): N write tasks stage under the scheduler's
        retry/speculation discipline (first task commit wins), the job
        commit publishes atomically (_SUCCESS last; overwrite = the
        deferred dir swap), and any failure aborts leak-free with
        pre-existing data untouched."""
        from spark_rapids_tpu.config import rapids_conf as rc
        from spark_rapids_tpu.io import commit as iocommit
        from spark_rapids_tpu.io.writers import WriteStats, write_task
        from spark_rapids_tpu.runtime.scheduler import (
            StageScheduler,
            Task,
        )

        session = self._df.session
        conf = getattr(session, "rapids_conf", None)
        committer = iocommit.JobCommitter(
            path, mode=self._mode, fmt=self._format, conf=conf,
            partition_by=self._partition_by or None,
            options=self._options)
        if not committer.setup_job():
            return None  # mode=ignore with existing output
        stats = WriteStats()
        try:
            table = self._df.collect_arrow()
            n = (conf.get(rc.WRITE_TASKS) if conf is not None
                 else rc.WRITE_TASKS.default)
            n = max(1, min(int(n), table.num_rows or 1))
            step = -(-max(table.num_rows, 1) // n)  # ceil division

            def make_run(i: int, piece):
                def run(attempt):
                    adir = committer.attempt_dir(i, attempt)
                    recs: list = []

                    def stage(rel, write_fn, rows):
                        recs.append(iocommit.stage_file(
                            adir, rel, rows, write_fn))

                    write_task(self._format, piece, adir, i,
                               self._partition_by or None, None,
                               options=self._options, stage=stage,
                               file_tag=committer.job_id)
                    return adir, recs

                return run

            tasks = [
                Task(i, run=make_run(i, table.slice(i * step, step)),
                     commit=lambda res, att, i=i:
                         committer.commit_task(i, res, stats),
                     abort=lambda att, i=i:
                         committer.abort_task(i, att),
                     lineage=f"write {self._format} task {i}")
                for i in range(n)]
            StageScheduler(conf, name=f"write-{self._format}",
                           max_parallel=n).run(tasks)
            committer.commit_job()
        except BaseException:
            committer.abort_job(reason="write failed")
            raise
        from spark_rapids_tpu.obs import telemetry as _tel

        _tel.merge_final(qid, {"write": {
            "bytes": stats.num_bytes, "files": stats.num_files,
            "rows": stats.num_rows, "jobs": 1,
            "commitMs": int(committer.commit_ms)}})
        return stats

    def parquet(self, path: str):
        return self.format("parquet").save(path)

    def orc(self, path: str):
        return self.format("orc").save(path)

    def csv(self, path: str):
        return self.format("csv").save(path)

    def json(self, path: str):
        return self.format("json").save(path)

    def avro(self, path: str):
        return self.format("avro").save(path)

    def delta(self, path: str):
        return self.format("delta").save(path)


class Row(dict):
    def __getattr__(self, k):
        try:
            return self[k]
        except KeyError as e:
            raise AttributeError(k) from e

    def __repr__(self):
        return "Row(" + ", ".join(f"{k}={v!r}" for k, v in
                                  self.items()) + ")"


class GroupedData:
    def __init__(self, df: DataFrame, cols, mode: str = "groupby",
                 sets=None):
        from spark_rapids_tpu.expr.windows import contains_window

        self.df = df
        self.mode = mode
        self._user_sets = sets
        self.grouping = [
            _named(df._col_expr(c), c if isinstance(c, str) else c.name)
            for c in cols]
        from spark_rapids_tpu.sqltypes import MapType

        for g in self.grouping:
            if contains_window(g):
                raise ValueError(
                    "window functions are not allowed as grouping keys; "
                    "materialize with select/withColumn first")
            if isinstance(g.dtype, MapType):
                raise ValueError(
                    "expression cannot be used as a grouping expression "
                    "because its data type is a map (Spark "
                    "EXPRESSION_TYPE_IS_NOT_ORDERABLE)")

    def agg(self, *cols) -> DataFrame:
        from spark_rapids_tpu.expr.aggregates import GroupingBit, GroupingID
        from spark_rapids_tpu.expr.windows import contains_window

        entries = []  # (base_expr, name); base is agg fn or marker
        for i, c in enumerate(cols):
            e = self.df._col_expr(c)
            if contains_window(e):
                raise ValueError(
                    "window functions are not allowed in groupBy.agg(); "
                    "use select/withColumn")
            base = e.children[0] if isinstance(e, Alias) else e
            if isinstance(base, (GroupingID, GroupingBit)):
                if self.mode == "groupby":
                    raise ValueError(
                        "grouping()/grouping_id() are only valid with "
                        "rollup/cube/groupingSets")
                if isinstance(e, Alias):
                    name = e.name
                elif isinstance(base, GroupingID):
                    name = "spark_grouping_id()"
                else:
                    name = f"grouping({base.children[0]!r})"
                entries.append((base, name))
                continue
            name = (e.name if isinstance(e, Alias)
                    else f"{base.name}({_input_name(base)})")
            assert isinstance(base, AggregateFunction), \
                f"agg() requires aggregate expressions, got {base!r}"
            entries.append((base, name))
        if self.mode != "groupby":
            return self._expand_agg(entries)
        aggs = [Alias(b, n) for b, n in entries]
        plan = L.Aggregate(self.grouping, aggs, self.df._plan)
        return DataFrame(plan, self.df.session)

    def _grouping_sets(self):
        """Index sets (into self.grouping) included per grouping set."""
        n = len(self.grouping)
        if self.mode == "rollup":
            return [frozenset(range(k)) for k in range(n, -1, -1)]
        if self.mode == "cube":
            from itertools import combinations

            out = []
            for k in range(n, -1, -1):
                out.extend(frozenset(s)
                           for s in combinations(range(n), k))
            return out
        # grouping_sets: user lists of column names
        by_name = {g.name: i for i, g in enumerate(self.grouping)}
        out = []
        for s in self._user_sets:
            try:
                out.append(frozenset(by_name[c] for c in s))
            except KeyError as e:
                raise ValueError(
                    f"grouping set column {e} not in groupingSets "
                    f"columns {sorted(by_name)}")
        return out

    def _expand_agg(self, entries) -> DataFrame:
        """rollup/cube/groupingSets: Expand (one projection per
        grouping set, null-masked keys + grouping-id) -> Aggregate over
        (keys + gid) -> Project dropping the internal gid key. The
        Spark lowering (ExpandExec), device-planned like everything
        else (reference GpuExpandExec.scala)."""
        from spark_rapids_tpu.expr.aggregates import (
            GroupingBit,
            GroupingID,
            Max,
        )
        from spark_rapids_tpu.expr.core import BoundReference, Literal
        from spark_rapids_tpu.expr.mathexpr import BitwiseAnd, ShiftRight
        from spark_rapids_tpu.sqltypes.datatypes import long as long_t

        child = self.df._plan
        cs = child.schema
        ncols = len(cs.fields)
        n = len(self.grouping)
        gid_ord = ncols + n
        sets = self._grouping_sets()
        # duplicate grouping sets must produce duplicate result rows
        # (Spark adds a grouping-set position to disambiguate)
        need_pos = len(set(sets)) < len(sets)
        projections = []
        for pos_i, s in enumerate(sets):
            gid_val = sum(1 << (n - 1 - i) for i in range(n)
                          if i not in s)
            proj = [Alias(BoundReference(j, f.dataType, f.nullable),
                          f.name)
                    for j, f in enumerate(cs.fields)]
            proj += [
                Alias(g.children[0] if i in s
                      else Literal(None, g.dtype), f"__g{i}")
                for i, g in enumerate(self.grouping)]
            proj.append(Alias(Literal(gid_val, long_t),
                              "spark_grouping_id"))
            if need_pos:
                proj.append(Alias(Literal(pos_i, long_t),
                                  "__grouping_pos"))
            projections.append(proj)
        expand = L.Expand(projections, child)
        new_grouping = [
            Alias(BoundReference(ncols + i, g.dtype, True), g.name)
            for i, g in enumerate(self.grouping)]
        new_grouping.append(
            Alias(BoundReference(gid_ord, long_t, False),
                  "spark_grouping_id"))
        if need_pos:
            new_grouping.append(
                Alias(BoundReference(gid_ord + 1, long_t, False),
                      "__grouping_pos"))
        gid_ref = BoundReference(gid_ord, long_t, False)
        agg_aliases = []
        for base, name in entries:
            if isinstance(base, GroupingID):
                agg_aliases.append(Alias(Max(gid_ref), name))
            elif isinstance(base, GroupingBit):
                i = self._grouping_index(base.children[0])
                bit = BitwiseAnd(
                    ShiftRight(gid_ref, Literal(n - 1 - i, long_t)),
                    Literal(1, long_t))
                agg_aliases.append(Alias(Max(bit), name))
            else:
                agg_aliases.append(Alias(base, name))
        agg_plan = L.Aggregate(new_grouping, agg_aliases, expand)
        nkeys = len(new_grouping)
        out = [Alias(BoundReference(i, g.dtype, True), g.name)
               for i, g in enumerate(self.grouping)]
        out += [
            Alias(BoundReference(nkeys + j, a.dtype,
                                 a.children[0].nullable), a.name)
            for j, a in enumerate(agg_aliases)]
        return DataFrame(L.Project(out, agg_plan), self.df.session)

    def _grouping_index(self, expr) -> int:
        key = expr.key()
        for i, g in enumerate(self.grouping):
            if g.children[0].key() == key:
                return i
        raise ValueError(
            f"grouping() argument {expr!r} is not a grouping column")

    def count(self) -> DataFrame:
        from spark_rapids_tpu.api import functions as F

        return self.agg(F.count("*").alias("count"))

    def applyInPandas(self, fn, schema) -> DataFrame:
        """Grouped-map pandas exchange: fn(pandas.DataFrame) ->
        pandas.DataFrame per key group
        (GpuFlatMapGroupsInPandasExec role)."""
        from spark_rapids_tpu.sqltypes.datatypes import parse_ddl_schema

        key_names = [g.name for g in self.grouping]
        if self.mode != "groupby":
            raise ValueError("applyInPandas requires plain groupBy()")
        return DataFrame(
            L.GroupedMapInPandas(key_names, fn,
                                 parse_ddl_schema(schema),
                                 self.df._plan),
            self.df.session)

    def cogroup(self, other: "GroupedData") -> "CoGroupedData":
        """Pair two grouped frames for cogrouped applyInPandas
        (GpuFlatMapCoGroupsInPandasExec role)."""
        return CoGroupedData(self, other)

    def _simple(self, fn, *cols) -> DataFrame:
        from spark_rapids_tpu.api import functions as F

        return self.agg(*[getattr(F, fn)(c).alias(f"{fn}({c})")
                          for c in cols])

    def sum(self, *cols):
        return self._simple("sum", *cols)

    def avg(self, *cols):
        return self._simple("avg", *cols)

    def min(self, *cols):
        return self._simple("min", *cols)

    def max(self, *cols):
        return self._simple("max", *cols)


def _input_name(fn: AggregateFunction) -> str:
    if not fn.children:
        return "*"
    c = fn.children[0]
    if isinstance(c, BoundReference):
        return f"#{c.ordinal}"
    return repr(c)


class CoGroupedData:
    def __init__(self, left: GroupedData, right: GroupedData):
        if [g.name for g in left.grouping] != \
                [g.name for g in right.grouping]:
            raise ValueError(
                "cogroup requires identical grouping column names")
        self.left = left
        self.right = right

    def applyInPandas(self, fn, schema) -> DataFrame:
        from spark_rapids_tpu.sqltypes.datatypes import parse_ddl_schema

        key_names = [g.name for g in self.left.grouping]
        return DataFrame(
            L.CoGroupedMapInPandas(key_names, fn,
                                   parse_ddl_schema(schema),
                                   self.left.df._plan,
                                   self.right.df._plan),
            self.left.df.session)
