"""Benchmark: the ENGINE end-to-end on the q5-shaped slice over a
CACHED table — the interactive-analytics loop.

Drives the full stack the way a user query does: session -> optimizer
-> planner (TpuOverrides) -> cached relation (HBM-resident via
`df.cache(storage="device")`, exec/relation_cache.py) -> fused
filter/project/hash-aggregate XLA programs (MXU segmented reductions)
-> final aggregate -> D2H collect, with the semaphore, reservation
ledger, and spill catalog all live.

Both sides run HOT over resident data: the engine queries the
device-cached relation; the CPU baseline (pyarrow) queries the same
table held in RAM. That is the apples-to-apples interactive scenario —
and the only defensible one on a tunneled device link (0.015-0.04 GB/s
H2D measured; any per-query re-upload would measure the tunnel, not
the engine). The one-time decode+upload cost is reported as `cold_s`,
and the link is characterized in the JSON so absolute numbers stay
diagnosable across environments.

Input is a >= 1 GiB parquet dataset (written once, cached in /tmp).
Reports the MEDIAN of N hot engine runs with inter-quartile dispersion
and the HBM-roofline fraction (input bytes / elapsed / device peak
memory bandwidth).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
"""

import json
import os
import statistics
import time

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc
import pyarrow.parquet as pq

ROWS = 36_000_000          # 4 x 8B columns ~= 1.07 GiB
FILES = 8
REPEATS = 5
# v4: PLAIN-encoded uncompressed parquet. The reference decodes parquet
# ON DEVICE (Table.readParquet, GpuParquetScan.scala:2619) so its host
# only moves bytes; the TPU engine gets the same property from PLAIN
# pages (io/parquet_plain.py stitches page payloads as zero-copy typed
# views — no host decompress/unpack pass on this single-core host).
# The CPU baseline reads the same files.
DATA_DIR = "/tmp/srtpu_bench_data_v4"

# peak HBM bandwidth per chip, bytes/s (public TPU specs; cpu backend
# gets a nominal DDR figure so the fraction stays meaningful)
_PEAK_BW = {
    "TPU v4": 1.2e12,
    "TPU v5e": 8.19e11,
    "TPU v5 lite": 8.19e11,
    "TPU v5p": 2.765e12,
    "TPU v6e": 1.64e12,
    "cpu": 5.0e10,
}


def ensure_data() -> int:
    """Write the dataset once; return total bytes (arrow buffer size)."""
    marker = os.path.join(DATA_DIR, "_DONE")
    per = ROWS // FILES
    if os.path.exists(marker):
        return int(open(marker).read())
    os.makedirs(DATA_DIR, exist_ok=True)
    rng = np.random.default_rng(0)
    total = 0
    for i in range(FILES):
        t = pa.table({
            "store": pa.array(rng.integers(0, 2000, per),
                              type=pa.int64()),
            "amount": pa.array(rng.random(per) * 100.0,
                               type=pa.float64()),
            "qty": pa.array(rng.integers(1, 100, per), type=pa.int64()),
            "day": pa.array(rng.integers(0, 365, per), type=pa.int64()),
        })
        total += t.nbytes
        pq.write_table(t, os.path.join(DATA_DIR, f"part-{i}.parquet"),
                       compression="NONE", use_dictionary=False,
                       row_group_size=per, data_page_size=64 << 20)
    with open(marker, "w") as f:
        f.write(str(total))
    return total


def engine_query(base):
    from spark_rapids_tpu.api import functions as F

    return (base
            .filter(F.col("amount") > 10.0)
            .select("store",
                    (F.col("amount") * F.col("qty")).alias("revenue"),
                    "amount")
            .groupBy("store")
            .agg(F.sum("revenue").alias("rev"),
                 F.avg("amount").alias("avg_amount"),
                 F.count("*").alias("sales")))


def cpu_query(t):
    f = t.filter(pc.greater(t.column("amount"), 10.0))
    rev = pc.multiply(f.column("amount"),
                      pc.cast(f.column("qty"), pa.float64()))
    work = pa.table({"store": f.column("store"), "revenue": rev,
                     "amount": f.column("amount")})
    return work.group_by("store").aggregate(
        [("revenue", "sum"), ("amount", "mean"), ("store", "count")])


def _probe_device_backend():
    """The TPU tunnel can wedge (jax.devices() then hangs forever in
    every process). Probe it in a killable subprocess BEFORE this
    process imports jax; fall back to the CPU backend so the bench
    always emits its JSON line."""
    import subprocess
    import sys

    try:
        r = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            capture_output=True, timeout=120)
        if r.returncode == 0:
            return None
    except subprocess.TimeoutExpired:
        pass
    print("# device backend unreachable; benchmarking on cpu",
          flush=True)
    return "cpu"


def main():
    fallback = _probe_device_backend()
    import jax

    if fallback:
        # the env var alone is not enough: site customization may call
        # jax.config.update("jax_platforms", ...) at interpreter start
        jax.config.update("jax_platforms", fallback)
    jax.config.update("jax_enable_x64", True)

    input_bytes = ensure_data()

    from spark_rapids_tpu.api.session import TpuSparkSession

    spark = TpuSparkSession({
        "spark.sql.shuffle.partitions": 8,
        # one decode chunk per file so the fused per-partition programs
        # compile once and every file rides the same shape bucket
        "spark.rapids.sql.reader.batchSizeRows": 1 << 23,
        "spark.rapids.sql.batchSizeRows": 1 << 23,
        # HBM-resident shuffle blocks: no host round trip per exchange
        # (used when the plan falls back to the per-operator engine)
        "spark.rapids.shuffle.mode": "DEVICE",
    })

    # ---- CPU baseline (pyarrow): HOT, over a RAM-resident table ----
    t0 = time.perf_counter()
    host_table = pq.read_table(DATA_DIR)
    cpu_cold_s = time.perf_counter() - t0  # decode cost, for reference
    cpu_times = []
    cpu_out = cpu_query(host_table)
    for _ in range(3):
        t0 = time.perf_counter()
        cpu_out = cpu_query(host_table)
        cpu_times.append(time.perf_counter() - t0)
    cpu_gbps = input_bytes / min(cpu_times) / 1e9

    # ---- engine: HOT, over the device-cached relation ----
    base = spark.read.parquet(DATA_DIR).cache(storage="device")
    df = engine_query(base)
    t0 = time.perf_counter()
    out = df.collect_arrow()  # cold: decode + upload + compiles
    cold_s = time.perf_counter() - t0
    assert out.num_rows == cpu_out.num_rows, (out.num_rows,
                                              cpu_out.num_rows)
    times = []
    for _ in range(REPEATS):
        t0 = time.perf_counter()
        out = df.collect_arrow()
        times.append(time.perf_counter() - t0)
    med = statistics.median(times)
    times_sorted = sorted(times)
    q1 = times_sorted[len(times) // 4]
    q3 = times_sorted[(3 * len(times)) // 4]
    spread_pct = 100.0 * (q1 and (q3 - q1) / med or 0.0)
    dev_gbps = input_bytes / med / 1e9

    dev = jax.devices()[0]
    kind = getattr(dev, "device_kind", dev.platform)
    peak = next((v for k, v in _PEAK_BW.items()
                 if k.lower() in str(kind).lower()),
                _PEAK_BW["cpu"])
    roofline = dev_gbps * 1e9 / peak

    # characterize the host<->device link so absolute numbers are
    # interpretable: tunneled/relayed devices add a fixed per-dispatch
    # roundtrip that dominates multi-operator pipelines
    probe = jax.device_put(np.zeros(1 << 20))
    jax.block_until_ready(probe)
    t0 = time.perf_counter()
    for _ in range(5):
        jax.device_get(probe[:8])
    rt_ms = (time.perf_counter() - t0) / 5 * 1000
    big = np.zeros(1 << 25)
    t0 = time.perf_counter()
    jax.block_until_ready(jax.device_put(big))
    h2d = big.nbytes / (time.perf_counter() - t0) / 1e9

    print(json.dumps({
        "metric": f"q5-slice engine throughput over device-cached table"
                  f" ({dev.platform}, {ROWS} rows,"
                  f" {input_bytes >> 20} MiB)",
        "value": round(dev_gbps, 3),
        "unit": "GB/s",
        "vs_baseline": round(dev_gbps / cpu_gbps, 3),
        "median_s": round(med, 3),
        "spread_pct": round(spread_pct, 1),
        "cold_s": round(cold_s, 2),
        "cpu_baseline_gbps": round(cpu_gbps, 3),
        "cpu_cold_read_s": round(cpu_cold_s, 2),
        "roofline_frac": round(roofline, 4),
        "device_kind": str(kind),
        "link_roundtrip_ms": round(rt_ms, 1),
        "link_h2d_gbps": round(h2d, 2),
    }))


if __name__ == "__main__":
    main()
