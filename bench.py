"""Benchmark: the ENGINE end-to-end on the full q5 shape —
scan + dimension JOIN + aggregate over a CACHED fact table, with a
string dimension column — the interactive-analytics loop.

Drives the full stack the way a user query does: session -> optimizer
-> planner (TpuOverrides) -> cached relation (HBM-resident via
`df.cache(storage="device")`, exec/relation_cache.py) -> fused
filter/lookup-join/project/hash-aggregate XLA programs (row-preserving
broadcast join gather + MXU segmented reductions) -> final aggregate
over the string dim key -> D2H collect, with the semaphore,
reservation ledger, and spill catalog all live.

Reports BOTH wall time and `compute_s`: the amortized per-iteration
time of N back-to-back pipeline dispatches with one final sync
(FusedSingleChipExecutor.execute_repeated), which removes the fixed
per-query link roundtrip (~100-180 ms on tunneled devices) and so
tracks the ENGINE, not the tunnel.

Both sides run HOT over resident data: the engine queries the
device-cached relation; the CPU baseline (pyarrow) queries the same
table held in RAM. That is the apples-to-apples interactive scenario —
and the only defensible one on a tunneled device link (0.015-0.04 GB/s
H2D measured; any per-query re-upload would measure the tunnel, not
the engine). The one-time decode+upload cost is reported as `cold_s`,
and the link is characterized in the JSON so absolute numbers stay
diagnosable across environments.

Input is a >= 1 GiB parquet dataset (written once, cached in /tmp).
Reports the MEDIAN of N hot engine runs with inter-quartile dispersion
and the HBM-roofline fraction (input bytes / elapsed / device peak
memory bandwidth).

Cold start is measured twice: `cold_s` (this process: decode + upload
+ first-time compiles) and `cold_warm_cache_s` — a FRESH subprocess
(`--cold-probe`) running the same query against the persistent
compilation cache this run just warmed (runtime/compile_cache.py), the
time-to-first-query a restarted service actually pays. Per-query
compile metrics (programs compiled / cache hits / warmup hits /
compile seconds / distinct variants) ride along from
session.last_execution. A duplicate-key dimension join variant
exercises the expanded blocking path (the lookup-join uniqueness bet
deliberately lost) so the expansion machinery has a perf number too.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
"""

import json
import os
import statistics
import subprocess
import sys
import time

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc
import pyarrow.parquet as pq

ROWS = int(os.environ.get("SRTPU_BENCH_ROWS", 36_000_000))
STORES = 2000              # 4 x 8B columns ~= 1.07 GiB at 36M rows
REGIONS = 12
FILES = 8
REPEATS = 5
COMPUTE_ITERS = 8
DUP_PER_STORE = 2          # duplicate-key dim: rows per store key
# v4: PLAIN-encoded uncompressed parquet. The reference decodes parquet
# ON DEVICE (Table.readParquet, GpuParquetScan.scala:2619) so its host
# only moves bytes; the TPU engine gets the same property from PLAIN
# pages (io/parquet_plain.py stitches page payloads as zero-copy typed
# views — no host decompress/unpack pass on this single-core host).
# The CPU baseline reads the same files.
DATA_DIR = f"/tmp/srtpu_bench_data_v7_{ROWS}"
DIM_DIR = f"/tmp/srtpu_bench_data_v7_{ROWS}_dim"
DUP_DIR = f"/tmp/srtpu_bench_data_v7_{ROWS}_dup"

# peak HBM bandwidth per chip, bytes/s: one source of truth with the
# telemetry roofline accounting (obs/telemetry.py DEVICE_PEAK_BW)
def _peak_bw_table():
    from spark_rapids_tpu.obs.telemetry import DEVICE_PEAK_BW

    return DEVICE_PEAK_BW


def ensure_data() -> int:
    """Write the datasets once; return fact bytes (arrow buffer size).

    Fact: 36M sales rows. Dim: one row per store with a STRING region
    column (the q5 star shape: the aggregate groups by a dimension
    attribute reached through the join)."""
    marker = os.path.join(DATA_DIR, "_DONE")
    per = ROWS // FILES
    if os.path.exists(marker):
        return int(open(marker).read())
    os.makedirs(DATA_DIR, exist_ok=True)
    os.makedirs(DIM_DIR, exist_ok=True)
    os.makedirs(DUP_DIR, exist_ok=True)
    rng = np.random.default_rng(0)
    total = 0
    for i in range(FILES):
        t = pa.table({
            "store": pa.array(rng.integers(0, STORES, per),
                              type=pa.int64()),
            "amount": pa.array(rng.random(per) * 100.0,
                               type=pa.float64()),
            "qty": pa.array(rng.integers(1, 100, per), type=pa.int64()),
            "day": pa.array(rng.integers(0, 365, per), type=pa.int64()),
        })
        total += t.nbytes
        pq.write_table(t, os.path.join(DATA_DIR, f"part-{i}.parquet"),
                       compression="NONE", use_dictionary=False,
                       row_group_size=per, data_page_size=64 << 20)
    dim = pa.table({
        "store": pa.array(np.arange(STORES), type=pa.int64()),
        "region": pa.array(
            [f"region_{i % REGIONS:02d}" for i in range(STORES)]),
        "opened_day": pa.array(rng.integers(0, 3650, STORES),
                               type=pa.int64()),
    })
    # v7: the string dim column is DICTIONARY-encoded so the encoded
    # execution path (columnar/encoding.py) engages — the region
    # payload crosses the link as codes + one 12-entry dictionary,
    # the canonical ROADMAP-item-2 beneficiary
    pq.write_table(dim, os.path.join(DIM_DIR, "dim-0.parquet"),
                   compression="NONE", use_dictionary=["region"])
    # duplicate-key dimension (DUP_PER_STORE rows per store): an inner
    # join against it is row-EXPANDING, so the lookup-join uniqueness
    # bet loses by construction and the fused engine re-lowers through
    # the expanded blocking join — the path the happy-path q5 never
    # touches
    dup = pa.table({
        "store": pa.array(np.repeat(np.arange(STORES), DUP_PER_STORE),
                          type=pa.int64()),
        "promo": pa.array(
            [f"promo_{i % 5:02d}"
             for i in range(STORES * DUP_PER_STORE)]),
        "discount": pa.array(
            rng.random(STORES * DUP_PER_STORE) * 0.3),
    })
    pq.write_table(dup, os.path.join(DUP_DIR, "dup-0.parquet"),
                   compression="NONE", use_dictionary=False)
    with open(marker, "w") as f:
        f.write(str(total))
    return total


def engine_query(base, dim):
    """q5 shape: fact scan -> filter -> broadcast join to the store
    dimension -> string-predicate filter on the dim attribute ->
    group by the STRING region column."""
    from spark_rapids_tpu.api import functions as F

    return (base
            .filter(F.col("amount") > 10.0)
            .join(dim, on="store", how="inner")
            .filter(F.col("region") != f"region_{REGIONS - 1:02d}")
            .select("region",
                    (F.col("amount") * F.col("qty")).alias("revenue"),
                    "amount")
            .groupBy("region")
            .agg(F.sum("revenue").alias("rev"),
                 F.avg("amount").alias("avg_amount"),
                 F.count("*").alias("sales")))


def dupjoin_query(base, dup):
    """Duplicate-key / row-expanding join variant: fact inner-join a
    dimension with DUP_PER_STORE rows per key, aggregate by the dup
    attribute — drives the expansion/blocking join path and its
    capacity machinery (the lookup-join lowering re-lowers expanded
    after the uniqueness flag trips)."""
    from spark_rapids_tpu.api import functions as F

    return (base
            .filter(F.col("amount") > 50.0)
            .join(dup, on="store", how="inner")
            .select("promo",
                    (F.col("amount") * F.col("discount"))
                    .alias("rebate"))
            .groupBy("promo")
            .agg(F.sum("rebate").alias("total_rebate"),
                 F.count("*").alias("n")))


def cpu_dupjoin_query(t, dup):
    f = t.filter(pc.greater(t.column("amount"), 50.0))
    j = f.join(dup, keys="store", join_type="inner")
    rebate = pc.multiply(j.column("amount"), j.column("discount"))
    work = pa.table({"promo": j.column("promo"), "rebate": rebate})
    return work.group_by("promo").aggregate(
        [("rebate", "sum"), ("promo", "count")])


def cpu_query(t, dim):
    f = t.filter(pc.greater(t.column("amount"), 10.0))
    j = f.join(dim, keys="store", join_type="inner")
    j = j.filter(pc.not_equal(j.column("region"),
                              f"region_{REGIONS - 1:02d}"))
    rev = pc.multiply(j.column("amount"),
                      pc.cast(j.column("qty"), pa.float64()))
    work = pa.table({"region": j.column("region"), "revenue": rev,
                     "amount": j.column("amount")})
    return work.group_by("region").aggregate(
        [("revenue", "sum"), ("amount", "mean"), ("region", "count")])


def _probe_device_backend():
    """The TPU tunnel can wedge (jax.devices() then hangs forever in
    every process). Probe it in a killable subprocess BEFORE this
    process imports jax; fall back to the CPU backend so the bench
    always emits its JSON line."""
    import subprocess
    import sys

    try:
        r = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            capture_output=True, timeout=120)
        if r.returncode == 0:
            return None
    except subprocess.TimeoutExpired:
        pass
    print("# device backend unreachable; benchmarking on cpu",
          flush=True)
    return "cpu"


def _session_conf():
    return {
        "spark.sql.shuffle.partitions": 8,
        # one decode chunk per file so the fused per-partition programs
        # compile once and every file rides the same shape bucket
        "spark.rapids.sql.reader.batchSizeRows": 1 << 23,
        "spark.rapids.sql.batchSizeRows": 1 << 23,
        # HBM-resident shuffle blocks: no host round trip per exchange
        # (used when the plan falls back to the per-operator engine)
        "spark.rapids.shuffle.mode": "DEVICE",
    }


def _admission_probe(spark) -> dict:
    """Governed burst against the live session: 4 concurrent copies of
    the aggregate query through a 1-slot admission controller with a
    2-deep queue (so real queueing and a real shed happen), then one
    mid-flight cancel — reporting queue-wait p50/p99, shed count, and
    cancel latency. The process controller is restored afterwards."""
    import statistics
    import threading

    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.runtime import admission
    from spark_rapids_tpu.runtime.errors import (
        QueryCancelledError,
        QueryRejectedError,
    )

    def q():
        return spark.read.parquet(DATA_DIR).groupBy("store").agg(
            F.sum("amount").alias("rev"))

    old = admission.get()
    ctrl = admission.AdmissionController(
        max_concurrent=1, queue_depth=2, queue_timeout_ms=120_000)
    admission.install(ctrl)
    waits_mark = len(admission.stats._waits)
    shed = [0]
    try:
        def worker():
            try:
                q().collect_arrow()
            except QueryRejectedError:
                shed[0] += 1

        threads = [threading.Thread(target=worker) for _ in range(4)]
        for i, t in enumerate(threads):
            t.start()
            time.sleep(0.01)  # deterministic arrival order
        for t in threads:
            t.join(600)
        waits = sorted(list(admission.stats._waits)[waits_mark:])

        # one mid-flight cancel: latency from cancel() to unwound;
        # earlier cancels when the query outruns the first attempt
        cancel_ms = None
        for delay in (0.005, 0.02, 0.08):
            err = []

            def victim():
                try:
                    q().collect_arrow()
                except QueryCancelledError:
                    err.append(True)

            t = threading.Thread(target=victim)
            t.start()
            time.sleep(delay)
            running = ctrl.running_table()
            if running:
                t0 = time.perf_counter()
                ctrl.cancel(running[0]["queryId"], "bench probe")
                t.join(600)
                if err:
                    cancel_ms = round(
                        (time.perf_counter() - t0) * 1000, 1)
                    break
            else:
                t.join(600)

        def pct(v, qq):
            if not v:
                return None
            return round(v[min(len(v) - 1,
                               int(round(qq * (len(v) - 1))))], 3)

        return {
            "queueWaitMsP50": pct(waits, 0.50),
            "queueWaitMsP99": pct(waits, 0.99),
            "queueWaitMsMean": (round(statistics.mean(waits), 3)
                                if waits else None),
            "shedCount": shed[0],
            "cancelLatencyMs": cancel_ms,
        }
    finally:
        admission.install(old)


def _sanitizer_probe(iters: int = 100) -> dict:
    """Correctness-tooling probe: drive constructed two-query permit
    cycles through a STANDALONE ConcurrencySanitizer (never installed
    process-wide, so the session under measurement is untouched) and
    time the closing-edge insertion — detection runs on edge insertion,
    so that call IS detect + victim-select + cancel-dispatch. Reports
    the unwind-dispatch p99, the detector counters, and the lint-rule
    inventory the static gate enforces."""
    from spark_rapids_tpu.runtime.cancellation import CancelToken
    from spark_rapids_tpu.runtime.sanitizer import (
        SEMAPHORE,
        ConcurrencySanitizer,
        quota_resource,
    )
    from spark_rapids_tpu.tools.lint.rules import all_rules

    san = ConcurrencySanitizer()
    quota = quota_resource()
    lat_ms = []
    for i in range(iters):
        a, b = 2 * i, 2 * i + 1
        tok = CancelToken(b)
        san.acquired(SEMAPHORE, a)
        san.acquired(quota, b)
        ra = san.begin_wait(quota, a)
        t0 = time.perf_counter()
        rb = san.begin_wait(SEMAPHORE, b, token=tok)  # closes the cycle
        lat_ms.append((time.perf_counter() - t0) * 1000)
        assert tok.cancelled, "victim was not unwound"
        san.end_wait(rb)
        san.end_wait(ra)
        san.released(quota, b)
        san.released(SEMAPHORE, a)
    san.check_clean()
    lat_ms.sort()
    snap = san.snapshot()
    return {
        "cyclesDetected": snap["cycles"],
        "victims": snap["victims"],
        "inversions": snap["inversions"],
        "victimUnwindMsP99": round(
            lat_ms[min(len(lat_ms) - 1,
                       int(round(0.99 * (len(lat_ms) - 1))))], 4),
        "lintRuleCount": len(all_rules()),
    }


def _serve_probe(spark) -> dict:
    """Serving-layer probe: a daemon over the live bench session,
    closed-loop clients across 3 tenants/priority classes sending the
    SAME parameterized aggregate with rotating bindings — the
    dashboard-traffic shape the structural plan cache exists for.
    Reports wire-level qps + latency percentiles, the shed rate, and
    the plan-cache hit ratio the nightly tracks."""
    import statistics
    import threading

    from spark_rapids_tpu.runtime.errors import QueryRejectedError
    from spark_rapids_tpu.serve.client import ServeClient
    from spark_rapids_tpu.serve.server import QueryServiceDaemon

    spec = {"op": "agg",
            "input": {"op": "filter",
                      "input": {"op": "parquet", "path": DATA_DIR},
                      "cond": {"fn": ">", "args": [{"col": "amount"},
                                                   {"param": "lo"}]}},
            "groupBy": ["store"],
            "aggs": [{"fn": "sum", "col": "amount", "as": "rev"}]}
    bindings = [{"lo": 10.0}, {"lo": 50.0}, {"lo": 90.0}]
    lat_ms, shed = [], [0]
    lock = threading.Lock()
    d = QueryServiceDaemon(session=spark).start()
    try:
        # warm the cache shape once so the measured loop is the
        # steady state a resident daemon actually serves
        with ServeClient.connect(d, "warm", "standard") as c:
            c.query(spec, params=bindings[0])

        def worker(tenant, pclass, rounds):
            with ServeClient.connect(d, tenant, pclass) as c:
                for r in range(rounds):
                    t0 = time.perf_counter()
                    try:
                        c.query(spec, params=bindings[r % 3])
                    except QueryRejectedError:
                        with lock:
                            shed[0] += 1
                        continue
                    with lock:
                        lat_ms.append(
                            (time.perf_counter() - t0) * 1000.0)

        rounds = 6
        tenants = [("acme", "interactive"), ("globex", "standard"),
                   ("initech", "batch")]
        t0 = time.perf_counter()
        threads = [threading.Thread(target=worker, args=(t, p, rounds))
                   for t, p in tenants]
        for t in threads:
            t.start()
        for t in threads:
            t.join(600)
        wall_s = time.perf_counter() - t0
        lat_ms.sort()

        def pct(q):
            if not lat_ms:
                return None
            return round(lat_ms[min(len(lat_ms) - 1,
                                    int(round(q * (len(lat_ms) - 1))))],
                         1)

        sent = len(lat_ms) + shed[0]
        return {
            "qps": round(len(lat_ms) / wall_s, 2) if wall_s else None,
            "latencyMsP50": pct(0.50),
            "latencyMsP99": pct(0.99),
            "latencyMsMean": (round(statistics.mean(lat_ms), 1)
                              if lat_ms else None),
            "shedRate": round(shed[0] / sent, 4) if sent else 0.0,
            "planCacheHitRatio":
                d.plan_cache.stats.snapshot()["hitRatio"],
            "tenants": len(tenants),
        }
    finally:
        d.stop()


def _fleet_probe() -> dict:
    """Fleet probe (opt-in via --fleet): qps of the SAME closed loop
    through the front door at 1/2/3 replicas, wire p50/p99, the
    failover blip a kill -9 opens (time from kill to the next
    completed query), and the affinity hit ratio vs the 1/N random
    baseline. Replicas are real subprocesses with their own sessions,
    so this is gated off the default bench run — the nightly passes
    --fleet and records the block."""
    import statistics
    import threading

    from spark_rapids_tpu.serve.client import ServeClient
    from spark_rapids_tpu.serve.router import FleetRouter
    from spark_rapids_tpu.serve.supervisor import ReplicaSupervisor

    # a modest dedicated dataset: this probe measures routing, wire
    # and failover overhead — not scan throughput (the main bench does)
    fleet_dir = "/tmp/srtpu_bench_fleet_v1"
    marker = os.path.join(fleet_dir, "_DONE")
    if not os.path.exists(marker):
        os.makedirs(fleet_dir, exist_ok=True)
        rng = np.random.default_rng(7)
        n = 200_000
        pq.write_table(pa.table({
            "store": pa.array(rng.integers(0, 64, n), pa.int64()),
            "amount": pa.array(rng.random(n) * 100.0),
        }), os.path.join(fleet_dir, "p0.parquet"))
        open(marker, "w").write("1")
    spec = {"op": "agg",
            "input": {"op": "filter",
                      "input": {"op": "parquet", "path": fleet_dir},
                      "cond": {"fn": ">", "args": [{"col": "amount"},
                                                   {"param": "lo"}]}},
            "groupBy": ["store"],
            "aggs": [{"fn": "sum", "col": "amount", "as": "rev"}]}
    bindings = [{"lo": 10.0}, {"lo": 50.0}, {"lo": 90.0}]
    tenants = ["acme", "globex", "initech"]

    def closed_loop(port, rounds):
        lat_ms, lock = [], threading.Lock()

        def worker(tenant):
            with ServeClient("127.0.0.1", port, tenant,
                             connect_attempts=10) as c:
                for r in range(rounds):
                    t0 = time.perf_counter()
                    c.query(spec, params=bindings[r % 3])
                    with lock:
                        lat_ms.append(
                            (time.perf_counter() - t0) * 1000.0)

        t0 = time.perf_counter()
        threads = [threading.Thread(target=worker, args=(t,))
                   for t in tenants]
        for t in threads:
            t.start()
        for t in threads:
            t.join(600)
        return lat_ms, time.perf_counter() - t0

    def pct(sorted_ms, q):
        if not sorted_ms:
            return None
        return round(sorted_ms[min(len(sorted_ms) - 1,
                                   int(round(q * (len(sorted_ms)
                                                  - 1))))], 1)

    sup = ReplicaSupervisor(conf={}, replica_confs=[{}, {}, {}])
    sup.start()
    out = {"scaling": {}}
    try:
        eps = sup.wait_ready(timeout_ms=300_000)
        # qps at 1/2/3 replicas: same loop, growing member list
        for n in (1, 2, 3):
            r = FleetRouter(endpoints=eps[:n]).start()
            try:
                closed_loop(r.port, rounds=1)  # warm each plan cache
                lat, wall = closed_loop(r.port, rounds=4)
                lat.sort()
                out["scaling"][str(n)] = {
                    "qps": round(len(lat) / wall, 2) if wall else None,
                    "latencyMsP50": pct(lat, 0.50),
                    "latencyMsP99": pct(lat, 0.99),
                }
            finally:
                r.stop()
        # affinity: a repeated spec pins to its rendezvous replica
        r = FleetRouter(
            supervisor=sup,
            conf={"spark.rapids.tpu.fleet.health.intervalMs": 100,
                  "spark.rapids.tpu.fleet.failover.maxAttempts":
                  6}).start()
        try:
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline and \
                    len(r.health()["routable"]) < 3:
                time.sleep(0.1)
            hits = {}
            with ServeClient("127.0.0.1", r.port, "acme") as c:
                for _ in range(9):
                    c.query(spec, params=bindings[0])
                    rep = c.last_result["replica"]
                    hits[rep] = hits.get(rep, 0) + 1
                out["affinityHitRatio"] = round(
                    max(hits.values()) / sum(hits.values()), 3)
                out["affinityRandomBaseline"] = round(1 / 3, 3)
                # failover blip: kill -9 the pinned replica, clock the
                # gap until the next completed query
                victim = max(hits, key=hits.get)
                t0 = time.perf_counter()
                sup.kill(victim)
                c.query(spec, params=bindings[0])
                out["failoverBlipMs"] = round(
                    (time.perf_counter() - t0) * 1000.0, 1)
                out["failoverLandedOn"] = c.last_result["replica"]
        finally:
            r.stop()
    finally:
        sup.stop()
    return out


def cold_probe():
    """--cold-probe: the warm-persistent-cache cold start. Runs in a
    FRESH process after the main bench warmed the compile cache, so it
    measures exactly what a restarted service pays for its first query:
    decode + upload + cache loads, no cold XLA compilation. Prints one
    JSON line the parent merges."""
    import jax

    jax.config.update("jax_enable_x64", True)
    ensure_data()

    from spark_rapids_tpu.api.session import TpuSparkSession
    from spark_rapids_tpu.runtime import compile_cache

    t0 = time.perf_counter()
    spark = TpuSparkSession(_session_conf())
    # the warmup thread races the scan I/O in production; the probe
    # joins it so the measurement is deterministic about what it
    # includes (warmup compile time counts toward cold start)
    compile_cache.warmup_join(300)
    base = spark.read.parquet(DATA_DIR).cache(storage="device")
    dim = spark.read.parquet(DIM_DIR).cache(storage="device")
    out = engine_query(base, dim).collect_arrow()
    dt = time.perf_counter() - t0
    print(json.dumps({
        "cold_warm_cache_s": round(dt, 2),
        "rows": out.num_rows,
        "engine": spark.last_execution["engine"],
        "compile": spark.last_execution["compile"],
    }))


def _run_cold_probe() -> dict:
    """Spawn the fresh-process probe; never let it sink the main
    report."""
    try:
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--cold-probe"],
            capture_output=True, timeout=900, text=True,
            cwd=os.path.dirname(os.path.abspath(__file__)))
        for line in reversed(r.stdout.splitlines()):
            if line.startswith("{"):
                return json.loads(line)
        print(f"# cold probe produced no JSON (rc={r.returncode}): "
              f"{r.stderr[-300:]!r}", flush=True)
    except Exception as e:
        print(f"# cold probe failed: {e!r}", flush=True)
    return {}


def _streaming_probe(spark, input_bytes: int) -> dict:
    """Out-of-core streaming executor (stream/): q5 over the PARQUET
    fact (no device cache) with the device window forced far below the
    table, so the bounded-window pipeline engages. Reports streamed
    throughput against the same roofline denominator as the main
    number, plus the pipeline's own health metrics: window high-water,
    partitions streamed, and the prefetch/H2D/compute overlap fraction
    (1.0 = the link was never idle while compute ran)."""
    window = max(64 << 20, input_bytes // 16)
    saved = {
        "spark.rapids.tpu.stream.enabled": "false",
        "spark.rapids.tpu.stream.window.maxBytes": "0",
        "spark.rapids.tpu.stream.window.quotaFraction": None,
    }
    try:
        for k in saved:
            try:
                saved[k] = spark.conf.get(k)
            except Exception:
                pass
        spark.conf.set("spark.rapids.tpu.stream.enabled", "true")
        spark.conf.set("spark.rapids.tpu.stream.window.maxBytes",
                       str(window))
        # trip the selection gate regardless of this host's free HBM
        spark.conf.set("spark.rapids.tpu.stream.window.quotaFraction",
                       "0.0001")
        base = spark.read.parquet(DATA_DIR)
        dim = spark.read.parquet(DIM_DIR)
        # the main loop device-cached the fact relation; structural
        # cache substitution would swap the probe's scan for the
        # resident copy and the streaming rung would (correctly) never
        # engage — park the cache entries for the duration instead of
        # releasing the residency the later blocks still measure
        cm = spark.cache_manager
        with cm._lock:
            parked, cm._entries = cm._entries, {}
        try:
            t0 = time.perf_counter()
            out = engine_query(base, dim).collect_arrow()
            dt = time.perf_counter() - t0
        finally:
            with cm._lock:
                parked.update(cm._entries)
                cm._entries = parked
        rec = spark.last_execution or {}
        tel = rec.get("telemetry") or {}
        return {
            "engine": rec.get("engine"),
            "windowBytes": window,
            "streamed_s": round(dt, 3),
            "streamed_gbps": round(input_bytes / dt / 1e9, 3),
            "rows": out.num_rows,
            "partitionsStreamed": tel.get("partitionsStreamed"),
            "windowPeakBytes": tel.get("windowPeakBytes"),
            "overlapFraction": tel.get("overlapFraction"),
        }
    finally:
        for k, v in saved.items():
            if v is not None:
                spark.conf.set(k, v)


def _write_probe(spark) -> dict:
    """Transactional write path (io/commit.py): steady-state GB/s per
    format pushing one in-memory table through the two-phase committer
    (attempt staging + fsync + rename + manifest publish), plus the
    job-commit latency p50/p99 over a burst of tiny jobs — the fixed
    publish cost every exactly-once job pays at the _SUCCESS point.
    GB/s is logical Arrow bytes over wall time, the same denominator
    convention as the read-side numbers."""
    import shutil
    import tempfile

    from spark_rapids_tpu.obs import events as obs_events

    n = 2_000_000
    rng = np.random.default_rng(11)
    t = pa.table({
        "a": pa.array(rng.integers(0, 1 << 40, n), type=pa.int64()),
        "b": pa.array(rng.random(n), type=pa.float64()),
        "s": pa.array([f"g{i % 97}" for i in range(n)],
                      type=pa.string()),
    })
    df = spark.createDataFrame(t)
    nbytes = t.nbytes
    root = tempfile.mkdtemp(prefix="srtpu_bench_write_")
    gbps = {}
    try:
        for fmt in ("parquet", "orc", "csv", "json", "avro",
                    "hivetext"):
            p = os.path.join(root, fmt)
            t0 = time.perf_counter()
            df.write.format(fmt).save(p)
            gbps[fmt] = round(
                nbytes / (time.perf_counter() - t0) / 1e9, 3)
        # publish latency: the write.commit event's commitMs covers
        # task promotion + manifest fsync alone, not data volume
        lat = []

        def tap(ev):
            if ev.get("event") == "write.commit":
                lat.append(float(ev.get("commitMs") or 0.0))

        bus = obs_events.get()
        if bus is not None:
            bus.subscribe(tap)
        small = spark.createDataFrame(t.slice(0, 10_000))
        try:
            for i in range(24):
                small.write.parquet(os.path.join(root, f"job{i}"))
        finally:
            if bus is not None:
                bus.unsubscribe(tap)
        lat.sort()

        def pct(q):
            return round(lat[min(len(lat) - 1, int(q * len(lat)))], 3)

        return {
            "tableMiB": round(nbytes / 2**20, 1),
            "gbps": gbps,
            "commitJobs": len(lat),
            "commit_p50_ms": pct(0.50) if lat else None,
            "commit_p99_ms": pct(0.99) if lat else None,
        }
    finally:
        shutil.rmtree(root, ignore_errors=True)


def _multichip_probe() -> dict:
    """Spawn the multichip scaling bench in its own process: q5 at
    1/2/4/8 shards on the mesh SPMD engine vs the default single-chip
    engine (spark_rapids_tpu/tools/multichip_bench.py). The subprocess
    forces a virtual 8-device mesh when this machine has fewer than 8
    real chips — device count is fixed at interpreter start, so the
    re-exec is mandatory, not an optimization. Never sinks the main
    report."""
    try:
        import jax

        env = dict(os.environ)
        if len(jax.devices()) < 8:
            env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                                " --xla_force_host_platform_device_count=8"
                                ).strip()
            env["JAX_PLATFORMS"] = "cpu"
        r = subprocess.run(
            [sys.executable, "-m",
             "spark_rapids_tpu.tools.multichip_bench"],
            capture_output=True, timeout=900, text=True, env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)))
        for line in reversed(r.stdout.splitlines()):
            if line.startswith("{"):
                return json.loads(line)
        print(f"# multichip probe produced no JSON (rc={r.returncode}):"
              f" {r.stderr[-300:]!r}", flush=True)
    except Exception as e:
        print(f"# multichip probe failed: {e!r}", flush=True)
    return {}


def main():
    fallback = _probe_device_backend()
    import jax

    if fallback:
        # the env var alone is not enough: site customization may call
        # jax.config.update("jax_platforms", ...) at interpreter start
        jax.config.update("jax_platforms", fallback)
    jax.config.update("jax_enable_x64", True)

    input_bytes = ensure_data()

    from spark_rapids_tpu.api.session import TpuSparkSession

    spark = TpuSparkSession(_session_conf())

    # ---- CPU baseline (pyarrow): HOT, over RAM-resident tables ----
    t0 = time.perf_counter()
    host_table = pq.read_table(DATA_DIR)
    cpu_cold_s = time.perf_counter() - t0  # decode cost, for reference
    host_dim = pq.read_table(DIM_DIR)
    cpu_times = []
    cpu_out = cpu_query(host_table, host_dim)
    for _ in range(3):
        t0 = time.perf_counter()
        cpu_out = cpu_query(host_table, host_dim)
        cpu_times.append(time.perf_counter() - t0)
    cpu_gbps = input_bytes / min(cpu_times) / 1e9

    # ---- engine: HOT, over device-cached relations ----
    base = spark.read.parquet(DATA_DIR).cache(storage="device")
    dim = spark.read.parquet(DIM_DIR).cache(storage="device")
    df = engine_query(base, dim)
    t0 = time.perf_counter()
    out = df.collect_arrow()  # cold: decode + upload + compiles
    cold_s = time.perf_counter() - t0
    # the COLD collect is where the uploads (and the encoded
    # representation's savings) happen — capture its ledger before the
    # warm repeats overwrite last_execution
    cold_telemetry = (spark.last_execution or {}).get("telemetry") or {}
    engine_used = spark.last_execution["engine"]
    cold_compile = spark.last_execution["compile"]
    assert out.num_rows == cpu_out.num_rows, (out.num_rows,
                                              cpu_out.num_rows)
    # correctness spot-check against the pyarrow oracle
    want = {r: round(v, 2) for r, v in zip(
        cpu_out.column("region").to_pylist(),
        cpu_out.column("revenue_sum").to_pylist())}
    got = {r: round(v, 2) for r, v in zip(
        out.column("region").to_pylist(), out.column("rev").to_pylist())}
    assert set(got) == set(want), (sorted(got), sorted(want))
    for r in want:
        assert abs(got[r] - want[r]) <= max(1e-6 * abs(want[r]), 1e-2), \
            (r, got[r], want[r])
    times = []
    for _ in range(REPEATS):
        t0 = time.perf_counter()
        out = df.collect_arrow()
        times.append(time.perf_counter() - t0)
    # capture the steady-state movement profile NOW: later probes
    # (dupjoin, admission burst) overwrite last_execution
    hot_telemetry = (spark.last_execution or {}).get("telemetry")
    med = statistics.median(times)
    times_sorted = sorted(times)
    q1 = times_sorted[len(times) // 4]
    q3 = times_sorted[(3 * len(times)) // 4]
    spread_pct = 100.0 * (q1 and (q3 - q1) / med or 0.0)
    dev_gbps = input_bytes / med / 1e9

    # ---- device-timed compute: N pipelined dispatches, one sync ----
    # (fused-engine-only measurement; if the wall-time query ran on a
    # different engine, or fused can't lower it, report nulls rather
    # than dying and losing the wall-time numbers)
    compute_s = compute_gbps = None
    if engine_used == "fused":
        from spark_rapids_tpu.exec.fused import FusedSingleChipExecutor

        try:
            phys, _ = df._physical()
            compute_s = FusedSingleChipExecutor(
                spark.rapids_conf).execute_repeated(phys, COMPUTE_ITERS)
            compute_gbps = input_bytes / compute_s / 1e9
        except Exception as e:  # never lose the wall-time report
            print(f"# compute_s unavailable: {e!r}", flush=True)

    # ---- duplicate-key join: the expansion/blocking path's number ----
    # (row-expanding inner join; the lookup-join uniqueness bet loses
    # and the fused engine re-lowers via the expanded blocking join)
    host_dup = pq.read_table(DUP_DIR)
    cpu_dup_out = cpu_dupjoin_query(host_table, host_dup)
    dup_med = dup_gbps = None
    dup_engine = None
    try:
        dup = spark.read.parquet(DUP_DIR).cache(storage="device")
        ddf = dupjoin_query(base, dup)
        dup_out = ddf.collect_arrow()  # cold: expanded-join compiles
        dup_engine = spark.last_execution["engine"]
        assert dup_out.num_rows == cpu_dup_out.num_rows, (
            dup_out.num_rows, cpu_dup_out.num_rows)
        want_rb = {p: round(v, 2) for p, v in zip(
            cpu_dup_out.column("promo").to_pylist(),
            cpu_dup_out.column("rebate_sum").to_pylist())}
        got_rb = {p: round(v, 2) for p, v in zip(
            dup_out.column("promo").to_pylist(),
            dup_out.column("total_rebate").to_pylist())}
        for p in want_rb:
            assert abs(got_rb[p] - want_rb[p]) <= max(
                1e-6 * abs(want_rb[p]), 1e-2), (p, got_rb[p], want_rb[p])
        dup_times = []
        for _ in range(3):
            t0 = time.perf_counter()
            ddf.collect_arrow()
            dup_times.append(time.perf_counter() - t0)
        dup_med = statistics.median(dup_times)
        dup_gbps = input_bytes / dup_med / 1e9
    except Exception as e:  # never lose the main report
        print(f"# dupjoin variant unavailable: {e!r}", flush=True)

    # ---- warm-persistent-cache cold start (fresh process) ----
    from spark_rapids_tpu.runtime import compile_cache

    compile_cache.flush()  # artifacts/index visible to the probe
    probe_rec = _run_cold_probe()

    dev = jax.devices()[0]
    kind = getattr(dev, "device_kind", dev.platform)
    peak_bw = _peak_bw_table()
    peak = next((v for k, v in peak_bw.items()
                 if k.lower() in str(kind).lower()),
                peak_bw["cpu"])
    roofline = dev_gbps * 1e9 / peak

    # characterize the host<->device link so absolute numbers are
    # interpretable: tunneled/relayed devices add a fixed per-dispatch
    # roundtrip that dominates multi-operator pipelines
    probe = jax.device_put(np.zeros(1 << 20))
    jax.block_until_ready(probe)
    t0 = time.perf_counter()
    for _ in range(5):
        jax.device_get(probe[:8])
    rt_ms = (time.perf_counter() - t0) / 5 * 1000
    big = np.zeros(1 << 25)
    t0 = time.perf_counter()
    jax.block_until_ready(jax.device_put(big))
    h2d = big.nbytes / (time.perf_counter() - t0) / 1e9

    # ---- admission/governance block: queue-wait percentiles, shed
    # ---- count and cancel latency of a governed burst, so the
    # ---- trajectory tracks what multi-tenant governance costs
    admission_block = None
    try:
        admission_block = _admission_probe(spark)
    except Exception as e:  # never lose the perf report
        print(f"# admission block unavailable: {e!r}", flush=True)

    # ---- data-movement telemetry block (obs/telemetry.py): per-query
    # ---- bytes moved by direction, device footprint and roofline —
    # ---- the success metric every bytes-moved optimization (ICI
    # ---- shuffle, compressed execution, out-of-core) will be judged
    # ---- against, per ROADMAP item 2
    telemetry_block = None
    try:
        from spark_rapids_tpu.obs import telemetry as _tel

        tel = hot_telemetry or {}
        telemetry_block = {
            # last HOT query of the main q5 loop (re-collect of the
            # device-cached relation: the steady-state movement profile)
            "bytesMovedByDirection": tel.get("bytesMoved"),
            "bytesMovedTotal": tel.get("bytesMovedTotal"),
            "bytesPerOutputRow": tel.get("bytesPerOutputRow"),
            "queryRooflineFrac": tel.get("rooflineFrac"),
            "queryLinkFrac": tel.get("linkFrac"),
            # process-level: the cached relations' device residency is
            # owned by the materializing (cold) query, so the process
            # high-water is the number that tracks real HBM pressure
            "hbmPeakBytes": max(
                tel.get("hbmPeakBytes") or 0,
                _tel.ledger.registry_view()["hbm"]["peakBytes"]),
            "processBytesMoved": _tel.ledger.registry_view()[
                "bytesMoved"],
            "linkPeaks": _tel.link_peaks(),
        }
    except Exception as e:  # never lose the perf report
        print(f"# telemetry block unavailable: {e!r}", flush=True)

    # ---- encoded-execution block (columnar/encoding.py): the
    # ---- bytes-moved win of dictionary-resident columns, measured
    # ---- two ways — the hot query's ledger savings/compression, and
    # ---- a direct encoded-vs-plain upload of the string dim (the
    # ---- canonical beneficiary): ROADMAP item 2's bytes-moved and
    # ---- effective-compression metrics
    encoded_block = None
    try:
        from spark_rapids_tpu.exec.fused import upload_narrowed
        from spark_rapids_tpu.obs import telemetry as _tel

        def h2d_bytes():
            with _tel.ledger._lock:
                cell = _tel.ledger.totals.get("h2d")
                return cell["bytes"] if cell else 0

        dim_enc_tbl = pq.read_table(DIM_DIR, read_dictionary=["region"])
        dim_plain_tbl = pq.read_table(DIM_DIR)
        b0 = h2d_bytes()
        enc_batch = upload_narrowed(dim_enc_tbl)
        dim_enc_bytes = h2d_bytes() - b0
        enc_engaged = any(c.is_encoded for c in enc_batch.columns)
        b0 = h2d_bytes()
        upload_narrowed(dim_plain_tbl)
        dim_plain_bytes = h2d_bytes() - b0
        tel = hot_telemetry or {}
        # effective roofline: the cold query DELIVERS the plain-
        # equivalent bytes while physically moving fewer — the
        # ROADMAP-item-2 "roofline_frac climbing" view of the win
        saved = cold_telemetry.get("bytesSavedEncoded")
        cold_rf = cold_telemetry.get("rooflineFrac")
        cold_total = cold_telemetry.get("bytesMovedTotal")
        eff_rf = (round(cold_rf * (cold_total + saved) / cold_total, 6)
                  if saved and cold_rf and cold_total else None)
        encoded_block = {
            "engaged": enc_engaged,
            # the canonical dim path: same table uploaded encoded vs
            # decoded (encoded includes the one-time dictionary)
            "dimUploadBytes": {"encoded": dim_enc_bytes,
                               "plain": dim_plain_bytes},
            "dimUploadRatio": (round(dim_plain_bytes
                                     / dim_enc_bytes, 3)
                               if dim_enc_bytes else None),
            # cold-query ledger (where the uploads happen): bytes the
            # encoded representation kept off the link/shuffle and the
            # resulting compression of those columns
            "bytesSavedEncoded": saved,
            "effectiveCompressionRatio": cold_telemetry.get(
                "effectiveCompressionRatio"),
            "coldRooflineFrac": cold_rf,
            "effectiveRooflineFrac": eff_rf,
            "rooflineFracDelta": (round(eff_rf - cold_rf, 6)
                                  if eff_rf is not None
                                  and cold_rf is not None else None),
            # steady-state (hot, device-cached) movement profile
            "bytesMovedByDirection": tel.get("bytesMoved"),
            "rooflineFrac": tel.get("rooflineFrac"),
        }
    except Exception as e:  # never lose the perf report
        print(f"# encoded block unavailable: {e!r}", flush=True)

    # ---- out-of-core streaming block (stream/): q5 over parquet with
    # ---- the device window forced to a fraction of the table —
    # ---- streamed GB/s vs the resident number above, window
    # ---- high-water, and the prefetch/compute overlap fraction that
    # ---- tells whether the pipeline ran at link speed
    streaming_block = None
    try:
        streaming_block = _streaming_probe(spark, input_bytes)
    except Exception as e:  # never lose the perf report
        print(f"# streaming block unavailable: {e!r}", flush=True)

    # ---- transactional write block (io/commit.py): GB/s per output
    # ---- format through the exactly-once committer and the
    # ---- job-commit (publish) latency p50/p99 — the nightly tracks
    # ---- what the two-phase protocol costs over plain file writes
    write_block = None
    try:
        write_block = _write_probe(spark)
    except Exception as e:  # never lose the perf report
        print(f"# write block unavailable: {e!r}", flush=True)

    # ---- obs attribution block: the perf trajectory should capture
    # ---- WHERE time went (top operators by device time, span-tree
    # ---- shape, event volume), not just the totals above
    obs_block = None
    try:
        from spark_rapids_tpu.obs import spans as obs_spans

        root = spark.obs.last_spans
        totals = obs_spans.operator_totals(root)
        top3 = sorted(totals.items(),
                      key=lambda kv: -kv[1]["deviceNs"])[:3]
        obs_block = {
            "eventCounts": dict(spark.obs.bus.counts),
            "spanTreeDepth": obs_spans.tree_depth(root),
            "topOperatorsByDeviceTime": [
                {"operator": name,
                 "deviceMs": round(t["deviceNs"] / 1e6, 3),
                 "wallMs": round(t["wallNs"] / 1e6, 3),
                 "calls": t["count"]}
                for name, t in top3],
        }
    except Exception as e:  # never lose the perf report
        print(f"# obs block unavailable: {e!r}", flush=True)

    # ---- concurrency-sanitizer block (runtime/sanitizer.py): cycle
    # ---- detection + victim-unwind latency of constructed deadlocks
    # ---- and the static-gate rule inventory — BENCH_r07+ tracks what
    # ---- the correctness tooling costs and covers. Runs AFTER the
    # ---- obs block so its probe events don't inflate eventCounts.
    sanitizer_block = None
    try:
        sanitizer_block = _sanitizer_probe()
    except Exception as e:  # never lose the perf report
        print(f"# sanitizer block unavailable: {e!r}", flush=True)

    # ---- multichip scaling block (PR 12): REAL q5 throughput at
    # ---- 1/2/4/8 shards on the mesh SPMD engine — scaling efficiency
    # ---- and the ledger's ici-vs-h2d byte split, replacing the old
    # ---- dry-run "OK" line with measured numbers
    multichip_block = None
    try:
        multichip_block = _multichip_probe() or None
    except Exception as e:  # never lose the perf report
        print(f"# multichip block unavailable: {e!r}", flush=True)

    # ---- serving-layer block (serve/): wire-level qps + latency of
    # ---- a 3-tenant closed loop through the resident daemon, shed
    # ---- rate and the structural plan-cache hit ratio — the nightly
    # ---- tracks what a served (vs embedded) query costs
    serve_block = None
    try:
        serve_block = _serve_probe(spark)
    except Exception as e:  # never lose the perf report
        print(f"# serve block unavailable: {e!r}", flush=True)

    # ---- fleet block (serve/router.py + serve/supervisor.py):
    # ---- qps at 1/2/3 subprocess replicas behind the front door,
    # ---- affinity hit ratio vs random, and the kill -9 failover
    # ---- blip — opt-in (--fleet) because it spawns real replica
    # ---- processes; the nightly passes it
    fleet_block = None
    if "--fleet" in sys.argv:
        try:
            fleet_block = _fleet_probe()
        except Exception as e:  # never lose the perf report
            print(f"# fleet block unavailable: {e!r}", flush=True)

    print(json.dumps({
        "metric": f"q5 join+agg engine throughput over device-cached"
                  f" tables ({dev.platform}, {ROWS} rows x {STORES}-row"
                  f" string dim, {input_bytes >> 20} MiB)",
        "value": round(dev_gbps, 3),
        "unit": "GB/s",
        "vs_baseline": round(dev_gbps / cpu_gbps, 3),
        "median_s": round(med, 3),
        "compute_s": None if compute_s is None else round(compute_s, 4),
        "compute_gbps": (None if compute_gbps is None
                         else round(compute_gbps, 3)),
        "engine": engine_used,
        "spread_pct": round(spread_pct, 1),
        "cold_s": round(cold_s, 2),
        "cold_warm_cache_s": probe_rec.get("cold_warm_cache_s"),
        "cold_warm_cache_compile": probe_rec.get("compile"),
        "compile_cold": cold_compile,
        "dupjoin_median_s": (None if dup_med is None
                             else round(dup_med, 3)),
        "dupjoin_gbps": (None if dup_gbps is None
                         else round(dup_gbps, 3)),
        "dupjoin_engine": dup_engine,
        "cpu_baseline_gbps": round(cpu_gbps, 3),
        "cpu_cold_read_s": round(cpu_cold_s, 2),
        "roofline_frac": round(roofline, 4),
        "device_kind": str(kind),
        "link_roundtrip_ms": round(rt_ms, 1),
        "link_h2d_gbps": round(h2d, 2),
        # failure-domain counters (PR 2): with chaos disabled these
        # should be ~zero and wall-clock within 2% of the pre-PR
        # numbers — BENCH_* history tracks robustness overhead; under
        # ci/chaos_check.sh they show the recovery machinery working
        "robustness": spark.robustness_metrics,
        # query-governance overhead (PR 5): queue waits / sheds /
        # cancel latency of a concurrent governed burst
        "admission": admission_block,
        # data-movement ledger (PR 6): per-query bytes moved by
        # direction, HBM footprint, per-query roofline — BENCH_r06+
        # records what every bytes-moved optimization must improve
        "telemetry": telemetry_block,
        # encoded execution (PR 8): dictionary-resident columns'
        # bytes-moved win — encoded-vs-plain dim upload, per-query
        # bytesSavedEncoded and effectiveCompressionRatio
        "encoded": encoded_block,
        # out-of-core streaming (stream/): q5 with the device window
        # forced below the table — streamed GB/s, window high-water,
        # partitions streamed, prefetch/compute overlap fraction
        "streaming": streaming_block,
        # transactional writes (io/commit.py): per-format GB/s through
        # the two-phase committer + job-commit latency p50/p99
        "write": write_block,
        # event/span attribution (obs/): top operators by device time,
        # span-tree depth, event volume — regression triage data
        "obs": obs_block,
        # correctness tooling (PR 7): deadlock-cycle detection +
        # victim-unwind latency, order-inversion audit, lint coverage
        "sanitizer": sanitizer_block,
        # multichip SPMD scaling (PR 12): q5 throughput at 1/2/4/8
        # shards, ici-resident shuffle byte split, scaling efficiency;
        # `hosts` sub-block (PR 17): 1x8 flat vs 2x4 host domains —
        # dcnBytes vs iciBytes and the hierarchical-agg DCN reduction
        "multichip": multichip_block,
        # serving layer (serve/): daemon qps, wire latency p50/p99,
        # shed rate, plan-cache hit ratio of a 3-tenant closed loop
        "serve": serve_block,
        # serving fleet (--fleet): front-door qps at 1/2/3 replicas,
        # affinity hit ratio, kill -9 failover blip
        "fleet": fleet_block,
    }))


if __name__ == "__main__":
    if "--cold-probe" in sys.argv:
        fb = _probe_device_backend()
        if fb:
            import jax

            jax.config.update("jax_platforms", fb)
        cold_probe()
    else:
        main()
