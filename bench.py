"""Benchmark: flagship q5-shaped columnar pipeline on the device.

Measures the fused scan->filter->project->hash-aggregate stage (the
TPC-DS q5 minimum slice, SURVEY.md section 7) as device throughput in
GB/s of columnar input processed, against a pyarrow CPU baseline running
the same query — the stand-in for the reference's CPU-Spark baseline
(BASELINE.md metric: per-chip GB/s columnar scan).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import sys
import time

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

ROWS = 4_000_000
REPEATS = 5


def build_table(rows: int) -> pa.Table:
    rng = np.random.default_rng(0)
    return pa.table({
        "store": pa.array(rng.integers(0, 200, rows), type=pa.int64()),
        "amount": pa.array(rng.random(rows) * 100.0, type=pa.float64()),
        "qty": pa.array(rng.integers(1, 100, rows), type=pa.int64()),
    })


def cpu_query(table: pa.Table):
    f = table.filter(pc.greater(table.column("amount"), 10.0))
    rev = pc.multiply(f.column("amount"), pc.cast(f.column("qty"),
                                                  pa.float64()))
    work = pa.table({"store": f.column("store"), "revenue": rev,
                     "amount": f.column("amount")})
    return work.group_by("store").aggregate(
        [("revenue", "sum"), ("amount", "mean"), ("store", "count")])


def main():
    import jax

    jax.config.update("jax_enable_x64", True)

    from spark_rapids_tpu.columnar import arrow_to_device

    import importlib.util
    import os

    entry_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "__graft_entry__.py")
    spec = importlib.util.spec_from_file_location("graft_entry", entry_path)
    ge = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(ge)

    table = build_table(ROWS)
    input_bytes = table.nbytes

    # ---- CPU baseline (pyarrow, the vectorized CPU engine) ----
    cpu_query(table.slice(0, 100_000))  # warm
    t0 = time.perf_counter()
    for _ in range(max(1, REPEATS // 2)):
        cpu_query(table)
    cpu_time = (time.perf_counter() - t0) / max(1, REPEATS // 2)
    cpu_gbps = input_bytes / cpu_time / 1e9

    # ---- device pipeline ----
    query_step, _ = ge.entry()
    batch = arrow_to_device(table)
    jitted = jax.jit(query_step)
    out = jitted(batch)  # compile + run
    jax.block_until_ready(jax.tree_util.tree_leaves(out))
    t0 = time.perf_counter()
    for _ in range(REPEATS):
        out = jitted(batch)
        jax.block_until_ready(jax.tree_util.tree_leaves(out))
    dev_time = (time.perf_counter() - t0) / REPEATS
    dev_gbps = input_bytes / dev_time / 1e9

    backend = jax.devices()[0].platform
    print(json.dumps({
        "metric": f"q5-slice columnar pipeline throughput ({backend}, "
                  f"{ROWS} rows)",
        "value": round(dev_gbps, 3),
        "unit": "GB/s",
        "vs_baseline": round(dev_gbps / cpu_gbps, 3),
    }))


if __name__ == "__main__":
    main()
